"""Cluster bootstrap — the TPU-native analog of the reference's TF_CONFIG path.

The reference synthesizes a ``TF_CONFIG`` env var from ``CLUSTER_SPEC`` /
``TASK_INDEX`` / ``JOB_NAME`` (mnist_keras_distributed.py:221-233) and relies on
TensorFlow's gRPC runtime to wire up ps/master/worker roles with per-role device
filters (mnist_keras_distributed.py:165-189).

On TPU there is no parameter-server data plane: every process is an equal SPMD
participant and the runtime is `jax.distributed` over DCN, with XLA collectives
over ICI inside a slice. This module therefore:

- accepts the *same environment contract* as the reference
  (``CLUSTER_SPEC``/``TASK_INDEX``/``JOB_NAME``, or a pre-built ``TF_CONFIG``),
  plus the native ``TFDE_COORDINATOR``/``TFDE_NUM_PROCESSES``/``TFDE_PROCESS_ID``
  variables and JAX's own defaults;
- maps roles onto SPMD ranks: ``master``/``chief`` -> process 0, ``worker`` i ->
  process i (+1 when a master exists), ``ps`` entries are *dropped* — their
  capability (sharded variable hosting) is provided synchronously by ZeRO-style
  optimizer-state sharding (see parallel/strategies.py, and SURVEY.md §7 "hard
  parts" for the documented async->sync semantic change);
- calls ``jax.distributed.initialize`` exactly once when a multi-process
  cluster is configured.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Optional

from tfde_tpu import knobs

log = logging.getLogger(__name__)

_INITIALIZED = False
#: the ClusterInfo the last bootstrap() resolved — what the running
#: process group was actually built from. The elastic layer diffs a fresh
#: resolve_cluster() against this to detect a scheduler that rewrote the
#: spec (TF_CONFIG / TFDE_*) between supervisor attempts.
_LAST_INFO: Optional["ClusterInfo"] = None


@dataclasses.dataclass(frozen=True)
class ClusterInfo:
    """Resolved identity of this process within the training cluster."""

    num_processes: int
    process_id: int
    coordinator_address: Optional[str]
    job_type: str  # 'chief' | 'worker' | 'local'
    task_index: int

    @property
    def is_chief(self) -> bool:
        """Chief = process 0, the reference's `worker 0` / `master` role.

        The reference gates TensorBoard launch and export on worker 0
        (mnist_keras_distributed.py:277-280); we gate all host-side side
        effects (checkpoint writes, event files, export) the same way.
        """
        return self.process_id == 0

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def _parse_tf_config() -> Optional[dict]:
    """Parse TF_CONFIG if present — reference contract at mnist_keras:165-189."""
    raw = os.environ.get("TF_CONFIG")
    if not raw:
        return None
    try:
        cfg = json.loads(raw)
    except json.JSONDecodeError as e:
        # Fail loudly: silently degrading would fan a configured N-host job
        # out into N independent single-host jobs.
        raise ValueError(f"TF_CONFIG is set but is not valid JSON: {e}") from e
    if "cluster" not in cfg:
        return None
    return cfg


def _synthesize_tf_config() -> Optional[dict]:
    """CLUSTER_SPEC/TASK_INDEX/JOB_NAME -> TF_CONFIG dict.

    Mirrors mnist_keras_distributed.py:221-233, including writing the
    synthesized TF_CONFIG back into the environment, but fixes the reference's
    ``NameError`` when CLUSTER_SPEC is unset with ``job_type`` used later
    (mnist_keras:224-225 vs :278) by always returning a well-defined config.
    """
    raw = os.environ.get("CLUSTER_SPEC")
    if not raw:
        return None
    try:
        cluster_spec = json.loads(raw)
    except json.JSONDecodeError as e:
        raise ValueError(f"CLUSTER_SPEC is set but is not valid JSON: {e}") from e
    job_index = int(os.environ.get("TASK_INDEX", "0"))
    job_type = os.environ.get("JOB_NAME", "worker")
    cfg = {"cluster": cluster_spec, "task": {"type": job_type, "index": job_index}}
    os.environ["TF_CONFIG"] = json.dumps(cfg)
    log.info("Distribution enabled: %s", os.environ["TF_CONFIG"])
    return cfg


def _rank_from_tf_config(cfg: dict) -> tuple[int, int, str, int, Optional[str]]:
    """Map a TF_CONFIG cluster onto SPMD ranks.

    ps tasks are dropped (no PS data plane on TPU — see module docstring);
    chief/master is rank 0; workers follow in index order.
    Returns (num_processes, process_id, job_type, task_index, coordinator).
    """
    cluster = cfg["cluster"]
    task = cfg.get("task", {"type": "worker", "index": 0})
    job_type = task.get("type", "worker")
    task_index = int(task.get("index", 0))

    chief_hosts = cluster.get("chief", []) or cluster.get("master", [])
    worker_hosts = cluster.get("worker", [])
    ps_hosts = cluster.get("ps", [])
    if ps_hosts:
        log.info(
            "Cluster spec lists %d ps tasks; TPU build provides their "
            "capability via sharded optimizer state (sync DP), ps processes "
            "are not ranked. See SURVEY.md §7.",
            len(ps_hosts),
        )

    ranked_hosts = list(chief_hosts) + list(worker_hosts)
    num_processes = max(len(ranked_hosts), 1)

    if job_type in ("chief", "master"):
        process_id = 0
        norm_type = "chief"
    elif job_type == "worker":
        process_id = len(chief_hosts) + task_index
        norm_type = "chief" if (not chief_hosts and task_index == 0) else "worker"
    elif job_type == "ps":
        raise RuntimeError(
            "This process was launched with JOB_NAME=ps. The TPU-native build "
            "has no parameter-server role: run only chief/worker tasks and the "
            "optimizer state will be sharded across them (ZeRO-style). "
            "See SURVEY.md §7."
        )
    else:
        process_id = task_index
        norm_type = job_type

    # Coordinator = first ranked host, on a port derived from its service port
    # (the jax.distributed service is a separate listener from any app port).
    coordinator = ranked_hosts[0] if ranked_hosts else None
    return num_processes, process_id, norm_type, task_index, coordinator


def resolve_cluster() -> ClusterInfo:
    """Resolve cluster identity from the environment without side effects."""
    # Native contract takes precedence.
    if os.environ.get("TFDE_NUM_PROCESSES"):
        # knobs.env_int warn-fallbacks on garbage: an unparseable world
        # size drops to the TF_CONFIG path instead of crashing bootstrap
        num = knobs.env_int("TFDE_NUM_PROCESSES")
        if num is not None:
            pid = knobs.env_int("TFDE_PROCESS_ID", 0)
            coord = knobs.env_str("TFDE_COORDINATOR")
            return ClusterInfo(num, pid, coord,
                               "chief" if pid == 0 else "worker", pid)

    cfg = _parse_tf_config() or _synthesize_tf_config()
    if cfg is None:
        log.info("Distribution is not enabled")  # mnist_keras:233
        return ClusterInfo(1, 0, None, "local", 0)

    num, pid, job_type, task_index, coord = _rank_from_tf_config(cfg)
    return ClusterInfo(num, pid, coord, job_type, task_index)


def coordinator_endpoint(coord: str, default_port: int = 8476) -> str:
    """host[:port] from the cluster spec -> the jax.distributed coordinator
    endpoint.

    The spec port belongs to the application's own service (in a genuine
    TF_CONFIG migration, the TF gRPC server — a leftover process bound to
    it would make init fail), so the coordinator listens on a DERIVED
    port: spec port + 1011, wrapped to stay in range. Deterministic, so
    every process computes the same endpoint from the same spec.
    `TFDE_COORD_PORT` overrides when the derived port is also taken.
    """
    tail = coord.rsplit("]")[-1]  # IPv6-bracket aware
    if ":" in tail:
        host, spec_port = coord.rsplit(":", 1)
        derived = int(spec_port) + 1011
        if derived > 65535:
            derived = int(spec_port) - 1011
    else:
        host, derived = coord, default_port
    port = knobs.env_int("TFDE_COORD_PORT", int(derived))
    return f"{host}:{port}"


def metrics_push_url(info: Optional[ClusterInfo] = None,
                     port: Optional[int] = None) -> Optional[str]:
    """Where a non-chief host pushes metric snapshots
    (observability/aggregate.MetricsPusher), derived from the same spec
    that placed the chief:

    - ``TFDE_METRICS_PUSH_URL`` wins outright (explicit endpoint —
      required when the chief's server fell back to an ephemeral port);
    - else the coordinator's *host* + ``TFDE_METRICS_PORT``/`port` — the
      chief runs next to the jax.distributed coordinator, and its metrics
      server listens on the port every process already agrees on.

    Returns None when neither is derivable (single-process, or no fixed
    metrics port configured) — callers treat that as "pushing disabled".
    """
    env = knobs.env_str("TFDE_METRICS_PUSH_URL")
    if env:
        return env
    if port is None:
        port = knobs.env_int("TFDE_METRICS_PORT")
    if not port:  # None or 0 (ephemeral): workers can't guess the binding
        return None
    info = info or resolve_cluster()
    if not info.is_distributed or not info.coordinator_address:
        return None
    coord = info.coordinator_address
    tail = coord.rsplit("]")[-1]  # IPv6-bracket aware, like coordinator_endpoint
    host = coord.rsplit(":", 1)[0] if ":" in tail else coord
    return f"http://{host}:{port}/push"


def last_info() -> Optional[ClusterInfo]:
    """The ClusterInfo the last `bootstrap()` call resolved (None before
    the first bootstrap). This is the *running* topology, as opposed to
    `resolve_cluster()` which re-reads the environment fresh."""
    return _LAST_INFO


def initialized() -> bool:
    """True while a `jax.distributed` runtime this module started is up."""
    return _INITIALIZED


#: True when _initialize_resilient built the runtime client itself (with
#: shutdown_on_destruction=False) — only then can an abandon-teardown
#: safely drop the client object without its destructor entering the
#: shutdown barrier
_RESILIENT_CLIENT = False
#: runtime clients/services abandoned by an elastic teardown — once a peer
#: died, neither can be shut down or destroyed without terminating the
#: survivor, so they are made immortal (permanent incref) and listed here
#: for introspection; the OS reclaims them at process exit
_ZOMBIE_CLIENTS: list = []


#: heartbeat window under which the coordination service never declares a
#: task dead on its own: peer-death detection belongs to the resilience
#: layer (health staleness -> elastic.note_peer_lost, collective errors),
#: which can actually survive it — the stock runtime's reaction to a dead
#: peer is LOG(FATAL) in every process, the exact opposite of elastic
#: training. ~12 days: effectively never, without integer-overflow risk.
_HEARTBEAT_INTERVAL_S = 1_000
_MAX_MISSING_HEARTBEATS = 1_000


def _initialize_resilient(coord: str, info: "ClusterInfo",
                          policy) -> bool:
    """Build the jax.distributed runtime with survivor-safe options the
    public `initialize()` does not expose: heartbeat windows long enough
    that the coordination service never declares a peer dead (the default
    reaction is process termination), and no graceful shutdown from the
    client destructor — so an abandon-teardown after a peer death cannot
    enter the doomed cluster-wide shutdown barrier. Returns False when
    this jax version's internals don't match — the caller falls back to
    the vanilla path."""
    global _RESILIENT_CLIENT
    try:
        from jax._src import distributed as jdist
        from jax._src.lib import xla_extension as xe

        state = jdist.global_state
        if state.client is not None:
            return True  # already up (re-entrant bootstrap)

        def build_and_connect():
            if info.process_id == 0 and state.service is None:
                bind = "[::]:" + coord.rsplit(":", 1)[1]
                state.service = xe.get_distributed_runtime_service(
                    bind, info.num_processes,
                    heartbeat_interval=_HEARTBEAT_INTERVAL_S,
                    max_missing_heartbeats=_MAX_MISSING_HEARTBEATS)
            client = xe.get_distributed_runtime_client(
                coord, info.process_id,
                heartbeat_interval=_HEARTBEAT_INTERVAL_S,
                max_missing_heartbeats=_MAX_MISSING_HEARTBEATS,
                shutdown_on_destruction=False,
                use_compression=True,
            )
            try:
                client.connect()
            except Exception:
                del client  # partial state must not leak into the retry
                raise
            state.client = client
            state.coordinator_address = coord
            state.process_id = info.process_id
            state.num_processes = info.num_processes
            if state.preemption_sync_manager is None:
                state.initialize_preemption_sync_manager()

        from tfde_tpu.resilience.policy import retry_call

        retry_call(
            build_and_connect,
            policy=policy,
            what="distributed runtime connect",
            counter="resilience/bootstrap_retries",
        )
        _RESILIENT_CLIENT = True
        return True
    except (ImportError, AttributeError, TypeError):
        # jax moved the internals: vanilla initialize still works, minus
        # the survive-a-dead-peer teardown
        log.warning("resilient distributed-runtime construction unavailable "
                    "on this jax; falling back to jax.distributed.initialize",
                    exc_info=True)
        return False


def shutdown(abandon: bool = False) -> None:
    """Tear down the distributed runtime so `bootstrap()` can run again —
    the first half of an elastic re-bootstrap (resilience/elastic.py).
    Safe when nothing was initialized; failures during teardown are logged
    and swallowed.

    `abandon=True` is the peer-is-dead path: the graceful shutdown
    protocol runs a cluster-wide barrier that can never complete once a
    task died (and the stock runtime LOG(FATAL)s the surviving process
    when it fails). Worse, ANY teardown of the old runtime is fatal: the
    client's error-polling thread reacts to its poll RPC being cancelled
    — which both `service.shutdown()` and client destruction cause — by
    terminating the process (client.h: "Terminating process because the
    JAX distributed service detected fatal errors"), and the Python
    `missed_heartbeat_callback` escape hatch crashes with std::bad_cast
    on this jaxlib (no Status caster). So abandoning PARKS the old
    client and service in a module-level zombie list — alive but
    disowned, their threads quiescent under the long heartbeat window —
    and the re-bootstrap moves to a fresh coordination port (see
    elastic.shrink_env) instead of re-binding the abandoned one."""
    global _INITIALIZED, _RESILIENT_CLIENT
    if not _INITIALIZED:
        return
    import jax

    if abandon:
        try:
            from jax._src import distributed as jdist

            state = jdist.global_state
            client, service = state.client, state.service
            state.client = None
            state.service = None
            state.preemption_sync_manager = None
            # back to the class defaults: backend factories consult these
            # (e.g. the CPU client wires gloo collectives through
            # global_state.client) and stale world numbers would make a
            # post-shrink world-1 backend demand a client we just parked
            state.process_id = 0
            state.num_processes = 1
            state.coordinator_address = None
            import ctypes

            for obj in (client, service):
                if obj is None:
                    continue
                # immortal, not merely parked: interpreter teardown would
                # otherwise run the destructors in arbitrary order, and a
                # dying service cancels the client's outstanding poll RPC
                # — which the poll thread answers with LOG(FATAL). The OS
                # reclaims both at process exit.
                ctypes.pythonapi.Py_IncRef(ctypes.py_object(obj))
                _ZOMBIE_CLIENTS.append(obj)
            log.warning(
                "abandoned the distributed runtime of the old topology "
                "(client%s parked; a dead peer makes any teardown fatal)",
                "+service" if service is not None else "")
        except Exception:
            log.warning("abandon-teardown failed (continuing)",
                        exc_info=True)
    else:
        try:
            jax.distributed.shutdown()
        except Exception:
            # a dead peer/coordinator makes the farewell barrier fail —
            # that is exactly the situation an elastic teardown is for
            log.warning("jax.distributed.shutdown failed (continuing "
                        "teardown)", exc_info=True)
    _INITIALIZED = False
    _RESILIENT_CLIENT = False
    from tfde_tpu.observability import flightrec

    flightrec.record("distributed_shutdown", abandoned=bool(abandon))


def bootstrap(coordinator_port: int = 8476, force: bool = False) -> ClusterInfo:
    """Resolve the cluster and initialize `jax.distributed` if multi-process.

    The TPU-native analog of the reference's cluster bootstrap + gRPC session
    construction (mnist_keras_distributed.py:221-233 + 165-189). Safe to call
    multiple times; initialization happens once. `force=True` is the
    re-entrant path (elastic re-bootstrap after a topology change): it
    tears down any prior runtime via `shutdown()` and re-initializes from
    a FRESH read of the environment — the caller (resilience/elastic.py)
    is responsible for having rewritten the env to the surviving hosts.
    """
    global _INITIALIZED, _LAST_INFO
    if force:
        shutdown()
    info = resolve_cluster()
    if not info.is_distributed:
        # a world that shrank to one process must build its next CPU
        # backend WITHOUT cross-process collectives (the gloo impl set on
        # the way up would demand the distributed client we abandoned)
        import jax

        try:
            jax.config.update("jax_cpu_collectives_implementation", "none")
        except (AttributeError, ValueError):
            pass
    if info.is_distributed and not _INITIALIZED:
        import jax

        coord = info.coordinator_address
        if coord:
            coord = coordinator_endpoint(coord, coordinator_port)
        # Multi-process over the CPU backend (tests, local rehearsal of a
        # pod topology) needs a cross-process collectives impl; older jax
        # ships gloo behind a config knob that newer jax dropped. Harmless
        # for TPU — the option only touches the CPU client.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass
        log.info(
            "jax.distributed.initialize(coordinator=%s, num_processes=%d, process_id=%d)",
            coord, info.num_processes, info.process_id,
        )
        # Pod bring-up is racy by nature: workers start before the
        # coordinator listens, DNS lags the scheduler. initialize() surfaces
        # that as RuntimeError (grpc deadline) — retried under the
        # operator's TFDE_RETRY_* policy with RuntimeError added, since a
        # worker that gives up on first connect strands the whole slice.
        import dataclasses as _dc

        from tfde_tpu.resilience.policy import policy_from_env, retry_call

        base = policy_from_env()
        policy = _dc.replace(
            base, retryable=tuple(base.retryable) + (RuntimeError,)
        )
        # survivor-safe construction first (long heartbeat window + an
        # abandonable client — the elastic teardown depends on both);
        # vanilla initialize only when jax's internals moved
        if not (coord and _initialize_resilient(coord, info, policy)):
            retry_call(
                jax.distributed.initialize,
                coordinator_address=coord,
                num_processes=info.num_processes,
                process_id=info.process_id,
                policy=policy,
                what="jax.distributed.initialize",
                counter="resilience/bootstrap_retries",
            )
        _INITIALIZED = True
        from tfde_tpu.observability import flightrec

        flightrec.record(
            "bootstrap", num_processes=info.num_processes,
            process_id=info.process_id, coordinator=coord,
        )
    _LAST_INFO = info
    from tfde_tpu.observability import metrics

    metrics.gauge("cluster/world_size").set(info.num_processes)
    return info
