"""Cluster bootstrap — the TPU-native analog of the reference's TF_CONFIG path.

The reference synthesizes a ``TF_CONFIG`` env var from ``CLUSTER_SPEC`` /
``TASK_INDEX`` / ``JOB_NAME`` (mnist_keras_distributed.py:221-233) and relies on
TensorFlow's gRPC runtime to wire up ps/master/worker roles with per-role device
filters (mnist_keras_distributed.py:165-189).

On TPU there is no parameter-server data plane: every process is an equal SPMD
participant and the runtime is `jax.distributed` over DCN, with XLA collectives
over ICI inside a slice. This module therefore:

- accepts the *same environment contract* as the reference
  (``CLUSTER_SPEC``/``TASK_INDEX``/``JOB_NAME``, or a pre-built ``TF_CONFIG``),
  plus the native ``TFDE_COORDINATOR``/``TFDE_NUM_PROCESSES``/``TFDE_PROCESS_ID``
  variables and JAX's own defaults;
- maps roles onto SPMD ranks: ``master``/``chief`` -> process 0, ``worker`` i ->
  process i (+1 when a master exists), ``ps`` entries are *dropped* — their
  capability (sharded variable hosting) is provided synchronously by ZeRO-style
  optimizer-state sharding (see parallel/strategies.py, and SURVEY.md §7 "hard
  parts" for the documented async->sync semantic change);
- calls ``jax.distributed.initialize`` exactly once when a multi-process
  cluster is configured.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Optional

from tfde_tpu import knobs

log = logging.getLogger(__name__)

_INITIALIZED = False


@dataclasses.dataclass(frozen=True)
class ClusterInfo:
    """Resolved identity of this process within the training cluster."""

    num_processes: int
    process_id: int
    coordinator_address: Optional[str]
    job_type: str  # 'chief' | 'worker' | 'local'
    task_index: int

    @property
    def is_chief(self) -> bool:
        """Chief = process 0, the reference's `worker 0` / `master` role.

        The reference gates TensorBoard launch and export on worker 0
        (mnist_keras_distributed.py:277-280); we gate all host-side side
        effects (checkpoint writes, event files, export) the same way.
        """
        return self.process_id == 0

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def _parse_tf_config() -> Optional[dict]:
    """Parse TF_CONFIG if present — reference contract at mnist_keras:165-189."""
    raw = os.environ.get("TF_CONFIG")
    if not raw:
        return None
    try:
        cfg = json.loads(raw)
    except json.JSONDecodeError as e:
        # Fail loudly: silently degrading would fan a configured N-host job
        # out into N independent single-host jobs.
        raise ValueError(f"TF_CONFIG is set but is not valid JSON: {e}") from e
    if "cluster" not in cfg:
        return None
    return cfg


def _synthesize_tf_config() -> Optional[dict]:
    """CLUSTER_SPEC/TASK_INDEX/JOB_NAME -> TF_CONFIG dict.

    Mirrors mnist_keras_distributed.py:221-233, including writing the
    synthesized TF_CONFIG back into the environment, but fixes the reference's
    ``NameError`` when CLUSTER_SPEC is unset with ``job_type`` used later
    (mnist_keras:224-225 vs :278) by always returning a well-defined config.
    """
    raw = os.environ.get("CLUSTER_SPEC")
    if not raw:
        return None
    try:
        cluster_spec = json.loads(raw)
    except json.JSONDecodeError as e:
        raise ValueError(f"CLUSTER_SPEC is set but is not valid JSON: {e}") from e
    job_index = int(os.environ.get("TASK_INDEX", "0"))
    job_type = os.environ.get("JOB_NAME", "worker")
    cfg = {"cluster": cluster_spec, "task": {"type": job_type, "index": job_index}}
    os.environ["TF_CONFIG"] = json.dumps(cfg)
    log.info("Distribution enabled: %s", os.environ["TF_CONFIG"])
    return cfg


def _rank_from_tf_config(cfg: dict) -> tuple[int, int, str, int, Optional[str]]:
    """Map a TF_CONFIG cluster onto SPMD ranks.

    ps tasks are dropped (no PS data plane on TPU — see module docstring);
    chief/master is rank 0; workers follow in index order.
    Returns (num_processes, process_id, job_type, task_index, coordinator).
    """
    cluster = cfg["cluster"]
    task = cfg.get("task", {"type": "worker", "index": 0})
    job_type = task.get("type", "worker")
    task_index = int(task.get("index", 0))

    chief_hosts = cluster.get("chief", []) or cluster.get("master", [])
    worker_hosts = cluster.get("worker", [])
    ps_hosts = cluster.get("ps", [])
    if ps_hosts:
        log.info(
            "Cluster spec lists %d ps tasks; TPU build provides their "
            "capability via sharded optimizer state (sync DP), ps processes "
            "are not ranked. See SURVEY.md §7.",
            len(ps_hosts),
        )

    ranked_hosts = list(chief_hosts) + list(worker_hosts)
    num_processes = max(len(ranked_hosts), 1)

    if job_type in ("chief", "master"):
        process_id = 0
        norm_type = "chief"
    elif job_type == "worker":
        process_id = len(chief_hosts) + task_index
        norm_type = "chief" if (not chief_hosts and task_index == 0) else "worker"
    elif job_type == "ps":
        raise RuntimeError(
            "This process was launched with JOB_NAME=ps. The TPU-native build "
            "has no parameter-server role: run only chief/worker tasks and the "
            "optimizer state will be sharded across them (ZeRO-style). "
            "See SURVEY.md §7."
        )
    else:
        process_id = task_index
        norm_type = job_type

    # Coordinator = first ranked host, on a port derived from its service port
    # (the jax.distributed service is a separate listener from any app port).
    coordinator = ranked_hosts[0] if ranked_hosts else None
    return num_processes, process_id, norm_type, task_index, coordinator


def resolve_cluster() -> ClusterInfo:
    """Resolve cluster identity from the environment without side effects."""
    # Native contract takes precedence.
    if os.environ.get("TFDE_NUM_PROCESSES"):
        # knobs.env_int warn-fallbacks on garbage: an unparseable world
        # size drops to the TF_CONFIG path instead of crashing bootstrap
        num = knobs.env_int("TFDE_NUM_PROCESSES")
        if num is not None:
            pid = knobs.env_int("TFDE_PROCESS_ID", 0)
            coord = knobs.env_str("TFDE_COORDINATOR")
            return ClusterInfo(num, pid, coord,
                               "chief" if pid == 0 else "worker", pid)

    cfg = _parse_tf_config() or _synthesize_tf_config()
    if cfg is None:
        log.info("Distribution is not enabled")  # mnist_keras:233
        return ClusterInfo(1, 0, None, "local", 0)

    num, pid, job_type, task_index, coord = _rank_from_tf_config(cfg)
    return ClusterInfo(num, pid, coord, job_type, task_index)


def coordinator_endpoint(coord: str, default_port: int = 8476) -> str:
    """host[:port] from the cluster spec -> the jax.distributed coordinator
    endpoint.

    The spec port belongs to the application's own service (in a genuine
    TF_CONFIG migration, the TF gRPC server — a leftover process bound to
    it would make init fail), so the coordinator listens on a DERIVED
    port: spec port + 1011, wrapped to stay in range. Deterministic, so
    every process computes the same endpoint from the same spec.
    `TFDE_COORD_PORT` overrides when the derived port is also taken.
    """
    tail = coord.rsplit("]")[-1]  # IPv6-bracket aware
    if ":" in tail:
        host, spec_port = coord.rsplit(":", 1)
        derived = int(spec_port) + 1011
        if derived > 65535:
            derived = int(spec_port) - 1011
    else:
        host, derived = coord, default_port
    port = knobs.env_int("TFDE_COORD_PORT", int(derived))
    return f"{host}:{port}"


def metrics_push_url(info: Optional[ClusterInfo] = None,
                     port: Optional[int] = None) -> Optional[str]:
    """Where a non-chief host pushes metric snapshots
    (observability/aggregate.MetricsPusher), derived from the same spec
    that placed the chief:

    - ``TFDE_METRICS_PUSH_URL`` wins outright (explicit endpoint —
      required when the chief's server fell back to an ephemeral port);
    - else the coordinator's *host* + ``TFDE_METRICS_PORT``/`port` — the
      chief runs next to the jax.distributed coordinator, and its metrics
      server listens on the port every process already agrees on.

    Returns None when neither is derivable (single-process, or no fixed
    metrics port configured) — callers treat that as "pushing disabled".
    """
    env = knobs.env_str("TFDE_METRICS_PUSH_URL")
    if env:
        return env
    if port is None:
        port = knobs.env_int("TFDE_METRICS_PORT")
    if not port:  # None or 0 (ephemeral): workers can't guess the binding
        return None
    info = info or resolve_cluster()
    if not info.is_distributed or not info.coordinator_address:
        return None
    coord = info.coordinator_address
    tail = coord.rsplit("]")[-1]  # IPv6-bracket aware, like coordinator_endpoint
    host = coord.rsplit(":", 1)[0] if ":" in tail else coord
    return f"http://{host}:{port}/push"


def bootstrap(coordinator_port: int = 8476) -> ClusterInfo:
    """Resolve the cluster and initialize `jax.distributed` if multi-process.

    The TPU-native analog of the reference's cluster bootstrap + gRPC session
    construction (mnist_keras_distributed.py:221-233 + 165-189). Safe to call
    multiple times; initialization happens once.
    """
    global _INITIALIZED
    info = resolve_cluster()
    if info.is_distributed and not _INITIALIZED:
        import jax

        coord = info.coordinator_address
        if coord:
            coord = coordinator_endpoint(coord, coordinator_port)
        # Multi-process over the CPU backend (tests, local rehearsal of a
        # pod topology) needs a cross-process collectives impl; older jax
        # ships gloo behind a config knob that newer jax dropped. Harmless
        # for TPU — the option only touches the CPU client.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass
        log.info(
            "jax.distributed.initialize(coordinator=%s, num_processes=%d, process_id=%d)",
            coord, info.num_processes, info.process_id,
        )
        # Pod bring-up is racy by nature: workers start before the
        # coordinator listens, DNS lags the scheduler. initialize() surfaces
        # that as RuntimeError (grpc deadline) — retried under the
        # operator's TFDE_RETRY_* policy with RuntimeError added, since a
        # worker that gives up on first connect strands the whole slice.
        import dataclasses as _dc

        from tfde_tpu.resilience.policy import policy_from_env, retry_call

        base = policy_from_env()
        policy = _dc.replace(
            base, retryable=tuple(base.retryable) + (RuntimeError,)
        )
        retry_call(
            jax.distributed.initialize,
            coordinator_address=coord,
            num_processes=info.num_processes,
            process_id=info.process_id,
            policy=policy,
            what="jax.distributed.initialize",
            counter="resilience/bootstrap_retries",
        )
        _INITIALIZED = True
        from tfde_tpu.observability import flightrec

        flightrec.record(
            "bootstrap", num_processes=info.num_processes,
            process_id=info.process_id, coordinator=coord,
        )
    return info
