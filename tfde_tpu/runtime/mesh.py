"""Device-mesh construction — the substrate every strategy shards over.

The reference's distribution strategies (MirroredStrategy,
MultiWorkerMirroredStrategy, ParameterServerStrategy — see SURVEY.md §2c) are
all expressed here as *axes of one device mesh*: data parallelism is an axis
named ``data``, ZeRO/FSDP weight sharding is ``fsdp``, tensor parallelism is
``tensor``, sequence/context parallelism is ``seq``, expert parallelism is
``expert``, pipeline is ``pipe``. XLA compiles collectives onto ICI links for
axes inside a slice and onto DCN for axes that span hosts — the replacement for
the reference's RING/NCCL all-reduce (distributed_with_keras.py:16) and gRPC
parameter-server runtime (tf2_mnist_distributed.py:189).

Axis ordering convention (outermost -> innermost): DCN-crossing axes first
(``data`` spans hosts), ICI-local axes last (``tensor``/``seq`` want the
fastest links). This matches jax.experimental.mesh_utils' hybrid mesh logic.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names, outermost-first.
AXIS_ORDER = ("pipe", "data", "fsdp", "expert", "seq", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name -> size; -1 means 'fill remaining'.

    Examples:
        MeshSpec({"data": -1})                      # pure DP over all devices
        MeshSpec({"data": -1, "fsdp": 4})           # DP x FSDP
        MeshSpec({"data": 2, "seq": 2, "tensor": 2})  # DP x SP x TP
    """

    shape: Mapping[str, int]

    def __post_init__(self):
        unknown = set(self.shape) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"Unknown mesh axes {unknown}; valid: {AXIS_ORDER}")
        fills = [n for n, s in self.shape.items() if s == -1]
        if len(fills) > 1:
            raise ValueError(f"At most one axis may be -1, got {fills}")

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Concrete axis sizes for n_devices, in canonical order."""
        sizes = dict(self.shape)
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if n_devices % fixed != 0:
            raise ValueError(
                f"mesh shape {dict(sizes)} does not divide {n_devices} devices"
            )
        for name, s in sizes.items():
            if s == -1:
                sizes[name] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh shape {sizes} (product {math.prod(sizes.values())}) "
                f"!= device count {n_devices}"
            )
        return {a: sizes[a] for a in AXIS_ORDER if a in sizes}

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        return make_mesh(self.shape, devices)


def make_mesh(
    shape: Mapping[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over all (or the given) devices.

    Device order: `jax.devices()` enumerates all processes' devices in process
    order, so placing host-spanning axes (``data``) outermost keeps each
    host's local devices contiguous in the innermost (ICI-heavy) axes — the
    layout that routes `psum` over the `data` axis through DCN-aware
    hierarchical collectives and `tensor`/`seq` collectives over ICI.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = MeshSpec(shape).resolve(len(devices))
    dev_array = np.asarray(devices).reshape(tuple(sizes.values()))
    return Mesh(dev_array, tuple(sizes.keys()))


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Pure data-parallel mesh — the MultiWorkerMirroredStrategy analog."""
    return make_mesh({"data": -1}, devices)


def local_mirrored_mesh() -> Mesh:
    """Single-host DP mesh over this process's local devices only.

    The MirroredStrategy analog (mnist_keras_distributed.py:243): replicas on
    the local chips, no cross-host axis.
    """
    return make_mesh({"data": -1}, jax.local_devices())
