"""Central registry of every ``TFDE_*`` environment knob.

Every environment variable the framework reads is declared here once —
name, type, allowed values, default, and a doc string — so that:

- a typo'd **value** warns and falls back to the default instead of
  silently changing behavior (the ``TFDE_FLASH`` pattern from
  `ops/attention.py`, now the house rule for every knob);
- a typo'd **name** (``TFDE_GRAD_TRANSPRT=int8``) is caught at import
  by :func:`warn_unknown_env`, instead of being ignored forever;
- the project lint (`tools/tfdelint.py`) can cross-check every
  ``os.environ`` read of a ``TFDE_*`` literal in the tree against this
  registry and fail on unregistered knobs;
- the README knob table is generated (:func:`table_md`), not
  hand-maintained.

Read sites keep their module-local grammar where one exists (the
``TFDE_TRACE`` capacity spec, the ``TFDE_PROFILE`` window, the
``TFDE_PREFIX_CACHE`` byte budget) — those are registered with
``kind='spec'`` and validated by their owners — but scalar knobs route
through the accessors below (:func:`env_str` / :func:`env_int` /
:func:`env_float` / :func:`env_choice` / :func:`env_flag`), which warn
once per (name, bad value) and return the registered default.

This module deliberately imports nothing from the rest of the package:
any tfde_tpu module may import it without cycles.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import warnings
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "Knob", "REGISTRY", "is_registered", "canonical_names",
    "env_str", "env_int", "env_float", "env_choice", "env_flag",
    "warn_unknown_env", "table_md",
]


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered environment knob."""

    #: full env-var name (``TFDE_GRAD_TRANSPORT``) — or, for a family,
    #: the shared prefix ending in ``_`` with ``prefix=True``
    #: (``TFDE_SLO_`` covers ``TFDE_SLO_TTFT_MS`` etc. in audits, but
    #: well-known members are registered individually too).
    name: str
    #: value shape: 'choice' (one of `choices`), 'int', 'float', 'flag'
    #: (boolean-ish on/off spellings), 'str' (free-form: paths, URLs),
    #: or 'spec' (module-local grammar; the owner validates).
    kind: str
    #: value used when the variable is unset OR unparseable (after a
    #: warning). None means "feature off / derive elsewhere".
    default: Any = None
    #: allowed spellings for kind='choice' (canonical first).
    choices: Tuple[str, ...] = ()
    #: one-line operator doc; rendered into the README table.
    doc: str = ""
    #: where the value is consumed (module path, for the table).
    owner: str = ""
    #: True when `name` is a family prefix (``TFDE_RETRY_``).
    prefix: bool = False


REGISTRY: Dict[str, Knob] = {}

_warn_lock = threading.Lock()
_warned: set = set()  # (name, raw-value) pairs already warned about


def _register(*knobs: Knob) -> None:
    for k in knobs:
        REGISTRY[k.name] = k


_register(
    # --- parallel ---------------------------------------------------------
    Knob("TFDE_GRAD_TRANSPORT", "choice", "fp32", ("fp32", "int8"),
         "Default gradient exchange: full-precision psum or blockwise-"
         "quantized int8 transport with error feedback.",
         "parallel/comms.py"),
    Knob("TFDE_OPT_SHARDING", "choice", "replicated", ("replicated", "shard"),
         "Default optimizer-state placement: replicated, or ZeRO row-"
         "sharded weight update (reduce-scatter grads, all-gather params).",
         "parallel/zero.py"),
    # --- ops --------------------------------------------------------------
    Knob("TFDE_FLASH", "spec", "auto",
         ("auto", "on", "off", "<int min_seq>"),
         "Flash-attention dispatch threshold: 'off' never, 'on'/'1' always "
         "(min_seq=1024 legacy spelling), 'auto'/'' the built-in ladder, an "
         "integer sets min_seq explicitly.",
         "ops/attention.py"),
    Knob("TFDE_FLASH_BWD", "choice", "jax", ("jax", "pallas"),
         "Flash-attention backward: 'jax' blockwise recurrence (measured "
         "faster on v5e) or the Pallas dKV/dQ kernel pair (MHA only).",
         "ops/flash_attention.py"),
    # --- training / runtime ----------------------------------------------
    Knob("TFDE_PROFILE", "spec", None,
         ("<start>", "<start>:<stop>", "every:N", "every:N:S"),
         "XLA profiler step window (traces land under the run's "
         "model_dir): one window of global steps ('100:110', or '100' "
         "for 10 steps) or a repeating capture ('every:1000:5').",
         "observability/profiler.py"),
    Knob("TFDE_PROFILE_", "spec", None, (),
         "Trigger-driven profiling family prefix (see members below).",
         "observability/profiler.py", prefix=True),
    Knob("TFDE_PROFILE_TRIGGERS", "flag", True, (),
         "Allow anomaly signals (SLO burn, straggler, recompile storm, "
         "sentry trip) to auto-arm bounded XProf captures; 'off' keeps "
         "the trigger hub silent.",
         "observability/profiler.py"),
    Knob("TFDE_PROFILE_COOLDOWN_S", "float", 120.0, (),
         "Minimum seconds between any two trigger-driven captures.",
         "observability/profiler.py"),
    Knob("TFDE_PROFILE_DEDUPE_S", "float", 600.0, (),
         "Per-reason re-fire suppression window, seconds — the same "
         "anomaly cannot arm a second capture within it.",
         "observability/profiler.py"),
    Knob("TFDE_PROFILE_SPAN", "int", 8, (),
         "Default capture span for triggered windows: train steps "
         "(StepWindowProfiler.arm) or serving decode rounds "
         "(RoundWindowProfiler).",
         "observability/profiler.py"),
    Knob("TFDE_PROFILE_RETAIN", "int", 8, (),
         "Profile artifacts retained under <model_dir>/debug/profiles/ "
         "before the oldest capture (meta + trace dir) is pruned.",
         "observability/profiler.py"),
    Knob("TFDE_PROFILE_BURN_THRESHOLD", "float", 10.0, (),
         "Fast-window SLO burn rate at which the tracker asks the "
         "trigger hub for a capture; <= 0 disables the burn trigger.",
         "observability/slo.py"),
    Knob("TFDE_METRICS_PORT", "int", None, (),
         "Fixed port for the chief's /metrics+/push HTTP server (unset or "
         "0 = ephemeral; workers then cannot derive a push URL).",
         "training/lifecycle.py, runtime/cluster.py"),
    Knob("TFDE_METRICS_PUSH_URL", "str", None, (),
         "Explicit aggregator endpoint for non-chief metric pushes; "
         "overrides the coordinator-host + TFDE_METRICS_PORT derivation.",
         "runtime/cluster.py"),
    Knob("TFDE_DATA_DIR", "str", None, (),
         "Local dataset cache directory searched before ~/.keras/datasets "
         "and /tmp/data.",
         "data/datasets.py"),
    Knob("TFDE_NATIVE_CACHE", "str", None, (),
         "Build cache directory for the native C++ loader "
         "(default ~/.cache/tfde_tpu).",
         "native/__init__.py"),
    # --- cluster identity -------------------------------------------------
    Knob("TFDE_NUM_PROCESSES", "int", None, (),
         "Native cluster contract: world size. Takes precedence over "
         "TF_CONFIG when set.",
         "runtime/cluster.py"),
    Knob("TFDE_PROCESS_ID", "int", None, (),
         "Native cluster contract: this host's rank (default 0).",
         "runtime/cluster.py, observability/flightrec.py"),
    Knob("TFDE_COORDINATOR", "str", None, (),
         "Native cluster contract: coordinator host[:port].",
         "runtime/cluster.py"),
    Knob("TFDE_COORD_PORT", "int", None, (),
         "Override for the derived jax.distributed coordinator port.",
         "runtime/cluster.py"),
    # --- resilience (family: validated by policy_from_env, which raises
    # loudly on garbage — pinned by tests/test_resilience_policy.py) ------
    Knob("TFDE_RETRY_", "spec", None, (),
         "Retry-policy family prefix (see members below).",
         "resilience/policy.py", prefix=True),
    Knob("TFDE_RETRY_MAX_ATTEMPTS", "int", 4, (),
         "Retry budget for library I/O paths; 1 disables retries.",
         "resilience/policy.py"),
    Knob("TFDE_RETRY_INITIAL_BACKOFF", "float", 0.5, (),
         "First backoff sleep, seconds.", "resilience/policy.py"),
    Knob("TFDE_RETRY_MAX_BACKOFF", "float", 30.0, (),
         "Backoff ceiling, seconds.", "resilience/policy.py"),
    Knob("TFDE_RETRY_DEADLINE", "float", None, (),
         "Total retry wall-clock budget, seconds (unset = attempts only).",
         "resilience/policy.py"),
    # --- elastic training -------------------------------------------------
    Knob("TFDE_ELASTIC", "flag", False, (),
         "Elastic topology-change handling in the supervisor: a failure "
         "classified TOPOLOGY shrinks the cluster to the surviving hosts "
         "and resumes from the latest checkpoint instead of dying.",
         "resilience/elastic.py"),
    Knob("TFDE_ELASTIC_", "spec", None, (),
         "Elastic-training family prefix (see members below).",
         "resilience/elastic.py", prefix=True),
    Knob("TFDE_ELASTIC_MAX_CHANGES", "int", 4, (),
         "Topology changes allowed across one supervised run before the "
         "supervisor aborts.",
         "resilience/elastic.py"),
    Knob("TFDE_ELASTIC_DETECT_TIMEOUT_S", "float", 5.0, (),
         "Heartbeat-staleness age, seconds, at which a silent host is "
         "registered as a topology suspect.",
         "resilience/elastic.py, resilience/health.py"),
    Knob("TFDE_ELASTIC_PRESUME_LOST", "flag", True, (),
         "When a collective dies with no identified peer, presume every "
         "other rank lost and shrink to self (a scheduler env rewrite "
         "always wins over presumption).",
         "resilience/elastic.py"),
    Knob("TFDE_ELASTIC_MIN_WORLD", "int", 1, (),
         "Abort instead of resuming when the surviving world size is "
         "smaller than this.",
         "resilience/elastic.py"),
    # --- observability ----------------------------------------------------
    Knob("TFDE_TRACE", "spec", None, ("off", "on", "<int capacity>"),
         "Per-request distributed tracing: off (default), on (default "
         "ring capacity), or an integer ring capacity.",
         "observability/trace.py"),
    Knob("TFDE_MEMWATCH", "choice", "on", ("on", "off", "full"),
         "Per-program memory ledger: estimate-only ('on'), disabled, or "
         "AOT-compiled measurement ('full'/'measured').",
         "observability/memwatch.py"),
    Knob("TFDE_SLO_", "spec", None, (),
         "SLO-objective family prefix (see members below).",
         "observability/slo.py", prefix=True),
    Knob("TFDE_SLO_TTFT_MS", "float", 500.0, (),
         "Time-to-first-token SLO threshold, milliseconds.",
         "observability/slo.py"),
    Knob("TFDE_SLO_TPOT_MS", "float", 200.0, (),
         "Time-per-output-token SLO threshold, milliseconds.",
         "observability/slo.py"),
    Knob("TFDE_SLO_OBJECTIVE", "float", 0.99, (),
         "Attainment objective in (0, 1) for burn-rate math.",
         "observability/slo.py"),
    # --- inference --------------------------------------------------------
    Knob("TFDE_PREFIX_CACHE", "spec", None, ("off", "on", "<int bytes>"),
         "Serving prefix-KV cache default for every ContinuousBatcher: "
         "off (default), on (default budget), or an integer byte budget.",
         "inference/prefix_cache.py"),
    Knob("TFDE_PAGED_KV", "flag", False, (),
         "Paged KV serving: replace the dense per-row KV slabs with one "
         "block-granular pool shared by the prefix trie and active decode "
         "rows (inference/paged.py). Off (default) keeps the dense path "
         "byte-identical.",
         "inference/paged.py, inference/server.py"),
    Knob("TFDE_KV_BLOCK", "int", 16, (),
         "KV block size in tokens — the single source of truth for both "
         "the prefix trie's chunk length and the paged pool's block "
         "granularity. Any positive value works; 16 matches the trie's "
         "historical chunking.",
         "inference/paged.py, inference/prefix_cache.py"),
    Knob("TFDE_PAGED_PREFILL_CHUNK", "int", 64, (),
         "Token chunk width of the single paged prefill program; cold "
         "and warm admission feed prompts through it chunk-by-chunk so "
         "one static program covers every (prompt length, rows) shape "
         "(clamped to max_len at batcher construction).",
         "inference/paged.py, inference/server.py"),
    Knob("TFDE_KV_QUANT", "choice", "fp", ("fp", "int8"),
         "KV-cache storage format for every ContinuousBatcher: fp "
         "(default, byte-identical full precision) or int8 — quantized "
         "payload + per-(position, kv-head) fp32 scale sidecars in "
         "every cache layout (dense slab, paged pool, prefix trie), "
         "dequantized inside the attention program "
         "(ops/quant.kv_quantize). ~2x KV headroom at bf16, ~3.8x at "
         "fp32, same static program count.",
         "models/transformer.py, inference/server.py"),
    Knob("TFDE_KV_DEFRAG_THRESHOLD", "float", 0.5, (),
         "Paged-pool fragmentation ratio (holes / occupied span of live "
         "block ids) above which an admission stall triggers one bounded "
         "defrag pass (pool compaction + device permute + table/trie "
         "remap). 0 disables stall-triggered defrag.",
         "inference/server.py, inference/paged.py"),
    Knob("TFDE_ADMIT_", "spec", None, (),
         "Serving admission-control family prefix (see members below); "
         "all caps default off, so admission control is opt-in.",
         "inference/admission.py", prefix=True),
    Knob("TFDE_ADMIT_MAX_QUEUE", "int", 0, (),
         "Max QUEUED requests per batcher before submit() answers "
         "QueueFull/429 (0 = unlimited; active rows don't count).",
         "inference/admission.py"),
    Knob("TFDE_ADMIT_MAX_QUEUED_TOKENS", "int", 0, (),
         "Max queued output-token backlog per batcher before submit() "
         "answers QueueFull/429 (0 = unlimited).",
         "inference/admission.py"),
    Knob("TFDE_ADMIT_TTFT_DEADLINE_MS", "float", 0.0, (),
         "Default TTFT deadline applied to requests that don't bring "
         "their own: a request still queued past it is shed at dequeue "
         "instead of prefilled (0 = no deadline shedding).",
         "inference/admission.py"),
    Knob("TFDE_BROWNOUT_", "spec", None, (),
         "Router brownout family prefix (see members below).",
         "inference/router.py", prefix=True),
    Knob("TFDE_BROWNOUT_BURN", "float", 8.0, (),
         "Fast-window TTFT burn rate at which the router starts shedding "
         "best_effort traffic (0 = brownout off).",
         "inference/router.py"),
    Knob("TFDE_BROWNOUT_BURN_BATCH", "float", 16.0, (),
         "Fast-window TTFT burn rate at which the router also sheds "
         "batch traffic; interactive is never brownout-shed.",
         "inference/router.py"),
    Knob("TFDE_ADMIT_KV_HEADROOM", "int", 0, (),
         "Minimum KV headroom, in rows, admission requires: submit() "
         "answers QueueFull/429 with a kv payload when the capacity "
         "model's headroom_rows falls below it (0 = memory gate off).",
         "inference/admission.py"),
    Knob("TFDE_BOOT_", "spec", None, (),
         "Boot & readiness observability family prefix (see members "
         "below).",
         "observability/boot.py, inference/router.py", prefix=True),
    Knob("TFDE_BOOT_READY_REQUIRE", "flag", True, (),
         "Router readiness gate: place traffic only on replicas whose "
         "/load reports state 'ready' (a replica the router has never "
         "snapshotted fails open). 'off' restores pre-readiness "
         "placement on any live replica.",
         "inference/router.py"),
    Knob("TFDE_BOOT_READY_GRACE_S", "float", 120.0, (),
         "Seconds a never-ready (still booting) replica may push stale "
         "or report not-ready before staleness is allowed to declare it "
         "down; a booting replica mid-compile-storm is busy, not dead.",
         "inference/router.py"),
    Knob("TFDE_USAGE_LOG", "spec", None, ("off", "on", "<path>"),
         "Per-request usage metering JSONL: off (default), on (write "
         "model_dir/metrics/usage_<host>.jsonl on each ReplicaServer), "
         "or an explicit file path.",
         "observability/capacity.py"),
    Knob("TFDE_CAPACITY_", "spec", None, (),
         "KV-capacity observability family prefix (see members below).",
         "observability/capacity.py", prefix=True),
    Knob("TFDE_CAPACITY_BUDGET_BYTES", "int", 0, (),
         "KV memory budget the headroom model folds against (0 = derive "
         "capacity from the dense slab itself: headroom is the free "
         "rows and their cells).",
         "observability/capacity.py"),
    Knob("TFDE_CAPACITY_USAGE_LOG_BYTES", "int", 8388608, (),
         "Byte bound on one usage JSONL log; an append that would "
         "overflow it drops the oldest records so the newest half of "
         "the bound survives.",
         "observability/capacity.py"),
    # --- static analysis / gates -----------------------------------------
    Knob("TFDE_HLOLINT", "flag", False, (),
         "Arm the lowered-program linter's collection seam: programs "
         "registered with memwatch/recompile are also offered to "
         "analysis.hlolint for interrogation (tools/lintgate.py sets it).",
         "tfde_tpu/analysis/hlolint.py"),
    Knob("TFDE_MEMGATE_INJECT", "flag", False, (),
         "Memgate self-test: seed a deliberate extra compile so the gate "
         "must fail (tools/tier1.sh uses it to prove the gate bites).",
         "tools/memgate.py"),
    Knob("TFDE_LINTGATE_INJECT", "flag", False, (),
         "Lintgate self-test: lint two seeded-broken programs (a stray "
         "host callback, a dropped donation) so the gate must fail.",
         "tools/lintgate.py"),
    Knob("TFDE_TRENDGATE_INJECT", "flag", False, (),
         "Trendgate self-test: append a synthetic BENCH round with every "
         "gated metric regressed past twice its slack so the gate must "
         "fail.",
         "tools/trendgate.py"),
    # --- bench driver ------------------------------------------------------
    Knob("TFDE_BENCH_", "spec", None, (),
         "Bench driver family prefix (see members below).",
         "bench.py", prefix=True),
    Knob("TFDE_BENCH_BUDGET_S", "float", 1200.0, (),
         "Total driver retry budget, seconds, across probes and attempts.",
         "bench.py"),
    Knob("TFDE_BENCH_ATTEMPT_TIMEOUT_S", "float", 900.0, (),
         "Per-attempt wall-clock timeout, seconds, for one full bench run.",
         "bench.py"),
    Knob("TFDE_BENCH_PROBE_TIMEOUT_S", "float", 120.0, (),
         "Hard timeout, seconds, on one backend-liveness probe subprocess "
         "(a hung TPU runtime init must not eat the budget).",
         "bench.py"),
    Knob("TFDE_BENCH_MAX_PROBE_FAILS", "int", 3, (),
         "Consecutive failed backend probes before the driver gives up "
         "with a skip reason instead of burning the remaining budget.",
         "bench.py"),
    Knob("TFDE_BENCH_ALLOW_CPU", "flag", False, (),
         "Let the measurement run on CPU and say so in the artifact "
         "(otherwise a CPU-only backend is an honest-zero skip).",
         "bench.py"),
    Knob("TFDE_BENCH_FORCE_CPU", "flag", False, (),
         "Force JAX_PLATFORMS=cpu for the bench (implies ALLOW_CPU): the "
         "smoke path of the driver and tier-1.",
         "bench.py"),
    Knob("TFDE_BENCH_SMOKE", "flag", False, (),
         "Tiny shapes, path validation only — numbers are not reportable.",
         "bench.py"),
    Knob("TFDE_BENCH_WATCH_OUT", "str", None, (),
         "Artifact path for --watch mode's first-open-window capture "
         "(default BENCH_builder_rNN.json next to bench.py).",
         "bench.py"),
)


def is_registered(name: str) -> bool:
    """True when `name` is a registered knob or a member of a registered
    prefix family (``TFDE_RETRY_FOO`` matches the ``TFDE_RETRY_`` family)."""
    if name in REGISTRY:
        return True
    return any(k.prefix and name.startswith(k.name) and name != k.name
               for k in REGISTRY.values())


def canonical_names() -> Tuple[str, ...]:
    """All registered knob names (families listed by their prefix)."""
    return tuple(sorted(REGISTRY))


def _warn_once(name: str, raw: str, why: str, fallback: Any) -> None:
    key = (name, raw, why)
    with _warn_lock:
        if key in _warned:
            return
        _warned.add(key)
    warnings.warn(
        f"{name}={raw!r} {why}; falling back to {fallback!r}",
        stacklevel=3,
    )


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Free-form string knob (paths, URLs). Empty string counts as unset."""
    knob = REGISTRY.get(name)
    if default is None and knob is not None:
        default = knob.default
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    """Integer knob; a non-integer value warns once and yields `default`."""
    knob = REGISTRY.get(name)
    if default is None and knob is not None:
        default = knob.default
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _warn_once(name, raw, "is not an integer", default)
        return default


def env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    """Float knob; a non-numeric value warns once and yields `default`."""
    knob = REGISTRY.get(name)
    if default is None and knob is not None:
        default = knob.default
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        return float(raw)
    except ValueError:
        _warn_once(name, raw, "is not a number", default)
        return default


def env_choice(name: str, default: Optional[str] = None,
               choices: Tuple[str, ...] = ()) -> Optional[str]:
    """Enumerated knob; an unrecognized spelling warns once and yields the
    default. Matching is case-insensitive on the stripped value."""
    knob = REGISTRY.get(name)
    if knob is not None:
        default = knob.default if default is None else default
        choices = choices or knob.choices
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    v = raw.strip().lower()
    if v in choices:
        return v
    _warn_once(name, raw, f"is not one of {choices}", default)
    return default


_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("", "0", "false", "no", "off")


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean-ish knob; unrecognized spellings warn once and yield the
    default."""
    knob = REGISTRY.get(name)
    if knob is not None and knob.default is not None:
        default = bool(knob.default)
    raw = os.environ.get(name)
    if raw is None:
        return default
    v = raw.strip().lower()
    if v in _TRUTHY:
        return True
    if v in _FALSY:
        return False
    _warn_once(name, raw, "is not a recognized on/off spelling", default)
    return default


_unknown_warned = False


def warn_unknown_env() -> Tuple[str, ...]:
    """Warn once per process about ``TFDE_*`` names in the environment that
    no knob registers — the ``TFDE_GRAD_TRANSPRT=int8`` typo class, which
    otherwise silently runs fp32. Returns the offending names (for tests).

    Called from ``tfde_tpu/__init__.py`` so any import of the package
    surfaces the typo immediately.
    """
    global _unknown_warned
    unknown = tuple(sorted(
        n for n in os.environ
        if n.startswith("TFDE_") and not is_registered(n)
    ))
    if unknown and not _unknown_warned:
        _unknown_warned = True
        known = ", ".join(n for n in canonical_names())
        warnings.warn(
            f"unrecognized TFDE_* environment variable(s): "
            f"{', '.join(unknown)} — not read by any registered knob "
            f"(registered: {known})",
            stacklevel=2,
        )
    return unknown


def table_md() -> str:
    """Markdown knob table for the README (generated, not hand-kept)."""
    lines = [
        "| Knob | Values | Default | Consumed by | Purpose |",
        "| --- | --- | --- | --- | --- |",
    ]
    for name in sorted(REGISTRY):
        k = REGISTRY[name]
        if k.prefix:
            continue  # members are listed individually
        vals = ", ".join(f"`{c}`" for c in k.choices) if k.choices else f"({k.kind})"
        default = "unset" if k.default is None else f"`{k.default}`"
        lines.append(
            f"| `{k.name}` | {vals} | {default} | `{k.owner}` | {k.doc} |")
    return "\n".join(lines)
