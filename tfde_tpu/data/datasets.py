"""Dataset sources: MNIST / CIFAR-10 class data with hermetic fallback.

The reference pulls MNIST via `tf.keras.datasets.mnist.load_data()`
(mnist_keras_distributed.py:207-208) or `tfds.load('mnist')`
(distributed_with_keras.py:25-28). This environment has zero network egress,
so the loaders here resolve, in order:

1. a local file (``$TFDE_DATA_DIR``, ``~/.keras/datasets``, ``/tmp/data``) in
   the standard ``mnist.npz`` / cifar pickle layout;
2. a **deterministic synthetic dataset** with the same shapes/dtypes and a
   real learnable structure (class-conditional glyph templates + noise +
   jitter), so integration tests can assert that loss *decreases* (SURVEY.md
   §4) and benchmarks exercise the identical compute/IO path.

All arrays follow the reference's conventions: images float in [0,1]
(mnist_keras:211), labels int in a column vector (mnist_keras:215-216).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Tuple

import numpy as np

from tfde_tpu import knobs

Arrays = Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]

_SEARCH_DIRS = [
    lambda: knobs.env_str("TFDE_DATA_DIR"),
    lambda: os.path.expanduser("~/.keras/datasets"),
    lambda: "/tmp/data",
]


def _find(name: str):
    for get in _SEARCH_DIRS:
        d = get()
        if d and (Path(d) / name).exists():
            return Path(d) / name
    return None


def _glyph_templates(num_classes: int, side: int, rng: np.random.Generator) -> np.ndarray:
    """Distinct smooth per-class templates: random low-frequency patterns.

    Built from a few random 2-D cosine modes per class — smooth, well-separated
    in pixel space, and trivially reproducible from the seed.
    """
    yy, xx = np.mgrid[0:side, 0:side] / side
    t = np.zeros((num_classes, side, side), np.float32)
    for c in range(num_classes):
        for _ in range(4):
            fx, fy = rng.integers(1, 5, size=2)
            phase = rng.uniform(0, 2 * np.pi, size=2)
            t[c] += np.cos(2 * np.pi * fx * xx + phase[0]) * np.cos(
                2 * np.pi * fy * yy + phase[1]
            )
        t[c] -= t[c].min()
        t[c] /= t[c].max() + 1e-8
    return t


def _synthetic_images(
    n_train: int, n_test: int, side: int, num_classes: int, seed: int, channels: int = 0
) -> Arrays:
    rng = np.random.default_rng(seed)
    templates = _glyph_templates(num_classes, side, rng)

    def make(n, rng):
        labels = rng.integers(0, num_classes, size=n).astype(np.int64)
        imgs = templates[labels].copy()
        # per-example jitter: random shift ±2 px and gaussian noise
        shifts = rng.integers(-2, 3, size=(n, 2))
        imgs = np.stack(
            [np.roll(np.roll(im, s0, 0), s1, 1) for im, (s0, s1) in zip(imgs, shifts)]
        )
        imgs += rng.normal(0, 0.25, imgs.shape).astype(np.float32)
        imgs = np.clip(imgs, 0, 1).astype(np.float32)
        if channels:
            imgs = np.repeat(imgs[..., None], channels, axis=-1)
        return imgs, labels.reshape(-1, 1)

    return make(n_train, rng), make(n_test, rng)


def mnist(flatten: bool = True, n_train: int = 60000, n_test: int = 10000) -> Arrays:
    """MNIST (or its hermetic synthetic stand-in): float [0,1], labels [N,1].

    `flatten=True` returns [N,784] as the Estimator paths consume
    (serving signature [None,784], mnist_keras:159); else [N,28,28,1]
    (distributed_with_keras.py models).
    """
    path = _find("mnist.npz")
    if path is not None:
        with np.load(path) as d:
            tr_x, tr_y = d["x_train"], d["y_train"]
            te_x, te_y = d["x_test"], d["y_test"]
        tr_x = (tr_x / 255.0).astype(np.float32)  # mnist_keras:211
        te_x = (te_x / 255.0).astype(np.float32)
        tr_y = np.asarray(tr_y).astype(np.int64).reshape(-1, 1)  # mnist_keras:215
        te_y = np.asarray(te_y).astype(np.int64).reshape(-1, 1)
        tr_x = tr_x[..., None]
        te_x = te_x[..., None]
        train, test = (tr_x[:n_train], tr_y[:n_train]), (te_x[:n_test], te_y[:n_test])
    else:
        train, test = _synthetic_images(n_train, n_test, 28, 10, seed=0, channels=1)
    if flatten:
        train = (train[0].reshape(len(train[0]), -1), train[1])
        test = (test[0].reshape(len(test[0]), -1), test[1])
    return train, test


def _load_npz(path, n_train: int, n_test: int) -> Arrays:
    """Standard npz layout (x_train/y_train/x_test/y_test, uint8 images) ->
    float [0,1] images, int64 [N,1] labels."""
    with np.load(path) as d:
        tr = (
            (d["x_train"] / 255.0).astype(np.float32)[:n_train],
            np.asarray(d["y_train"]).astype(np.int64).reshape(-1, 1)[:n_train],
        )
        te = (
            (d["x_test"] / 255.0).astype(np.float32)[:n_test],
            np.asarray(d["y_test"]).astype(np.int64).reshape(-1, 1)[:n_test],
        )
    return tr, te


def cifar10(n_train: int = 50000, n_test: int = 10000) -> Arrays:
    """CIFAR-10 class data: [N,32,32,3] float [0,1], labels [N,1].

    Scale config `CIFAR-10 ResNet-50` (BASELINE.json configs[2]). Resolves a
    local ``cifar10.npz`` (keys x_train/y_train/x_test/y_test, uint8 images)
    from the standard search dirs first; synthetic stand-in otherwise.
    """
    path = _find("cifar10.npz")
    if path is not None:
        return _load_npz(path, n_train, n_test)
    train, test = _synthetic_images(n_train, n_test, 32, 10, seed=1)
    tr = np.repeat(train[0][..., None], 3, axis=-1), train[1]
    te = np.repeat(test[0][..., None], 3, axis=-1), test[1]
    return tr, te


def imagenet(
    n_train: int = 10000,
    n_test: int = 1000,
    side: int = 224,
    num_classes: int = 1000,
) -> Arrays:
    """ImageNet-shaped data for the ViT-B/16 FSDP config (BASELINE.json
    configs[3]): [N,side,side,3] float [0,1], labels [N,1].

    Resolves a local ``imagenet.npz`` (x_train/y_train/x_test/y_test uint8)
    first; otherwise the deterministic synthetic generator — same
    class-conditional structure as the MNIST/CIFAR stand-ins so training
    measurably learns (SURVEY.md §4).
    """
    path = _find("imagenet.npz")
    if path is not None:
        tr, te = _load_npz(path, n_train, n_test)
        if tr[0].shape[1] == side and tr[1].max() < num_classes:
            return tr, te
        import logging

        logging.getLogger(__name__).warning(
            "%s is %dpx with labels up to %d but %dpx/%d classes were "
            "requested; using the synthetic generator instead",
            path, tr[0].shape[1], int(tr[1].max()), side, num_classes,
        )
    train, test = _synthetic_images(
        n_train, n_test, side, num_classes, seed=3, channels=3
    )
    return train, test


_DOWNLOADS = {
    # canonical keras-datasets mirror; the file the reference's
    # tf.keras.datasets.mnist.load_data() fetches (mnist_keras:207-208)
    "mnist": {
        "url": "https://storage.googleapis.com/tensorflow/tf-keras-datasets/mnist.npz",
        "sha256": "731c5ac602752760c8e48fbffcf8c3b850d9dc2a2aedcf2cc48468fc17b673d1",
        "filename": "mnist.npz",
    },
    # official CIFAR-10 python batches; converted to the cifar10.npz
    # layout the loader resolves
    "cifar10": {
        "url": "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
        "sha256": "6d958be074577803d12ecdefd02955f39262c83c16fe9348329d7fe0b5c001ce",
        "filename": "cifar-10-python.tar.gz",
    },
}


def _convert_cifar_tarball(tar_path: Path, out_path: Path) -> None:
    """cifar-10-python.tar.gz (pickled batches) -> cifar10.npz
    (x_train/y_train/x_test/y_test uint8), the `_load_npz` layout."""
    import pickle
    import tarfile

    xs, ys, xt, yt = [], [], None, None
    with tarfile.open(tar_path, "r:gz") as tf:
        for member in tf.getmembers():
            base = os.path.basename(member.name)
            if not (base.startswith("data_batch") or base == "test_batch"):
                continue
            with tf.extractfile(member) as f:
                d = pickle.load(f, encoding="bytes")
            x = (
                np.asarray(d[b"data"], np.uint8)
                .reshape(-1, 3, 32, 32)
                .transpose(0, 2, 3, 1)
            )
            y = np.asarray(d[b"labels"], np.int64)
            if base == "test_batch":
                xt, yt = x, y
            else:
                xs.append(x)
                ys.append(y)
    if not xs or xt is None:
        raise ValueError(f"{tar_path} does not look like cifar-10-python")
    np.savez_compressed(
        out_path,
        x_train=np.concatenate(xs),
        y_train=np.concatenate(ys),
        x_test=xt,
        y_test=yt,
    )


def download(name: str, dest_dir: str = None, timeout: float = 600.0) -> str:
    """Opt-in dataset fetch into the standard local layout; returns the
    resolved dataset file path.

    Parity with the reference's network acquisition
    (`tf.keras.datasets.mnist.load_data()` at mnist_keras:207-208,
    `tfds.load('mnist', data_dir='/tmp/data')` at dwk:25-28) for machines
    WITH egress — never automatic: the loaders above stay hermetic
    (local file, else synthetic) and this function is the explicit knob
    (`python -m tfde_tpu.data.datasets mnist`). The payload is
    sha256-verified before it is installed; a mismatch deletes the
    download and raises.
    """
    if name not in _DOWNLOADS:
        raise ValueError(
            f"unknown dataset {name!r}; available: {sorted(_DOWNLOADS)}"
        )
    spec = _DOWNLOADS[name]
    dest = Path(
        dest_dir
        or knobs.env_str("TFDE_DATA_DIR")
        or os.path.expanduser("~/.keras/datasets")
    )
    dest.mkdir(parents=True, exist_ok=True)
    final = dest / f"{name}.npz"
    if final.exists():
        return str(final)

    import hashlib
    import urllib.request

    from tfde_tpu.resilience.policy import policy_from_env, retry_call

    tmp = dest / (spec["filename"] + ".download")

    def fetch() -> str:
        """One full download attempt; restarted from byte 0 on failure so a
        half-written tmp file never poisons the digest. urllib raises
        URLError (an OSError) on network faults -> retryable."""
        h = hashlib.sha256()
        with urllib.request.urlopen(spec["url"], timeout=timeout) as r, \
                open(tmp, "wb") as f:
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
                f.write(chunk)
        return h.hexdigest()

    digest = retry_call(
        fetch, policy=policy_from_env(), what=f"download({name})",
        counter="resilience/download_retries",
    )
    if digest != spec["sha256"]:
        tmp.unlink(missing_ok=True)
        raise ValueError(
            f"{name}: checksum mismatch for {spec['url']}: got {digest}, "
            f"expected {spec['sha256']} — refusing to install a corrupted "
            f"or tampered download"
        )
    if name == "cifar10":
        _convert_cifar_tarball(tmp, final)
        tmp.unlink()
    else:
        os.replace(tmp, final)
    return str(final)


def synthetic_tokens(
    n: int, seq_len: int, vocab: int = 30522, seed: int = 2
) -> np.ndarray:
    """Token id sequences for the BERT-base MLM config (BASELINE.json
    configs[4]): a Markov-ish stream so MLM has learnable structure."""
    rng = np.random.default_rng(seed)
    # transitions concentrated on a per-token successor set => predictable
    base = rng.integers(0, vocab, size=(n, seq_len), dtype=np.int32)
    succ = (np.arange(vocab, dtype=np.int32) * 31 + 7) % vocab
    for t in range(1, seq_len):
        follow = rng.random((n,)) < 0.7
        base[follow, t] = succ[base[follow, t - 1]]
    return base


if __name__ == "__main__":  # python -m tfde_tpu.data.datasets mnist [dir]
    import sys

    if len(sys.argv) < 2 or sys.argv[1] not in _DOWNLOADS:
        print(f"usage: python -m tfde_tpu.data.datasets "
              f"{{{'|'.join(sorted(_DOWNLOADS))}}} [dest_dir]",
              file=sys.stderr)
        sys.exit(2)
    print(download(sys.argv[1], sys.argv[2] if len(sys.argv) > 2 else None))
