"""Host data pipeline — the tf.data capability (SURVEY.md §2b row 3), TPU-native.

`Dataset` reproduces the reference pipelines' semantics
(shuffle/repeat/batch/prefetch/cache/shard — mnist_keras_distributed.py:123-148,
distributed_with_keras.py:18-30,54-57); `device.device_prefetch` adds the
on-device double-buffered feed; `datasets` provides MNIST/CIFAR-class sources
with a deterministic synthetic fallback for hermetic (zero-egress) runs.
"""

from tfde_tpu.data.pipeline import Dataset, AutoShardPolicy  # noqa: F401
from tfde_tpu.data.device import device_prefetch  # noqa: F401
from tfde_tpu.data.tfrecord import (  # noqa: F401
    TFRecordWriter,
    read_tfrecord,
    tfrecord_dataset,
    write_tfrecord,
)
from tfde_tpu.data.streaming import (  # noqa: F401
    StreamingTFRecordLoader,
    shard_files,
)
from tfde_tpu.data.packing import (  # noqa: F401
    pack_documents,
    packed_labels,
    packed_next_token_loss,
)
from tfde_tpu.data.text import (  # noqa: F401
    load_tokenizer,
    packed_text_batches,
    read_documents,
    tokenize_documents,
)
