"""Raw text -> packed LM training batches: the end-to-end text input
pipeline (tokenize offline, pack with data/packing.py, stream fixed-shape
batches).

The reference's data story starts at numpy arrays / TFDS
(/root/reference/mnist_keras_distributed.py:123-148); for the language
families this framework adds, training starts at text files. Everything
is host-side numpy + an offline transformers tokenizer (a LOCAL
save_pretrained() directory — nothing downloads), producing the static
[B, S] token + segment-id batches the packed training path consumes
(models/gpt.py segment_ids, data/packing.packed_next_token_loss).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tfde_tpu.data.packing import pack_documents


def load_tokenizer(tokenizer_dir: str):
    """Offline AutoTokenizer from a local save_pretrained() directory
    (the serve_gpt.py convention — this CLI surface never downloads)."""
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(tokenizer_dir,
                                         local_files_only=True)


def read_documents(
    paths: Sequence[str],
    split: str = "paragraph",
) -> List[str]:
    """Text files -> document strings. split: 'paragraph' (blank-line
    separated — the common pretraining convention), 'line', or 'file'.
    Empty documents are dropped."""
    docs: List[str] = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            content = f.read()
        if split == "file":
            parts = [content]
        elif split == "line":
            parts = content.splitlines()
        elif split == "paragraph":
            parts = content.split("\n\n")
        else:
            raise ValueError(
                f"split must be 'paragraph', 'line' or 'file', got "
                f"{split!r}"
            )
        docs.extend(p.strip() for p in parts if p.strip())
    return docs


def tokenize_documents(
    docs: Sequence[str],
    tokenizer,
    append_eos: bool = True,
    vocab_limit: Optional[int] = None,
) -> List[np.ndarray]:
    """Documents -> int32 token arrays. append_eos terminates each
    document with the tokenizer's eos (documents pack back-to-back, and
    the model should learn where one ends). vocab_limit (the model's
    vocab_size) makes an oversized tokenizer fail HERE with the ids
    named, not as a device-side gather surprise mid-training."""
    eos = None
    if append_eos:
        eos = tokenizer.eos_token_id
        if eos is None:
            raise ValueError(
                "append_eos=True but the tokenizer has no eos_token — a "
                "model trained on unterminated documents never learns to "
                "stop; pass append_eos=False to pack without terminators"
            )
    out: List[np.ndarray] = []
    for d in docs:
        ids = tokenizer(d, add_special_tokens=False)["input_ids"]
        if eos is not None:
            ids = list(ids) + [eos]
        if not ids:
            continue
        arr = np.asarray(ids, np.int32)
        if vocab_limit is not None and arr.max() >= vocab_limit:
            raise ValueError(
                f"token id {int(arr.max())} >= model vocab {vocab_limit}: "
                f"tokenizer and model do not match"
            )
        out.append(arr)
    return out


def packed_text_batches(
    paths: Sequence[str],
    tokenizer,
    seq_len: int,
    batch_size: int,
    split: str = "paragraph",
    append_eos: bool = True,
    vocab_limit: Optional[int] = None,
    seed: int = 0,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """The whole pipeline as one infinite batch stream: read -> tokenize
    -> pack once, then yield shuffled (tokens [B, S], segment_ids [B, S])
    batches forever (rows re-shuffled each epoch; the final partial batch
    of an epoch is dropped, keeping shapes static).

    Feed each yielded pair to `packed_next_token_loss` via
    `make_custom_train_step` — examples/gpt_lm.py's --packed loss path.
    """
    docs = read_documents(paths, split=split)
    if not docs:
        raise ValueError(f"no documents found in {list(paths)!r}")
    token_docs = tokenize_documents(docs, tokenizer,
                                    append_eos=append_eos,
                                    vocab_limit=vocab_limit)
    tokens, seg = pack_documents(token_docs, seq_len)
    if len(tokens) < batch_size:
        # replicate rows up to one batch rather than failing a small
        # corpus — smoke configs and tests hit this constantly
        reps = -(-batch_size // len(tokens))
        tokens = np.tile(tokens, (reps, 1))
        seg = np.tile(seg, (reps, 1))
    # the tested shuffle/repeat/batch fast path (data/pipeline.py) — one
    # stream implementation, not a hand-rolled twin that can drift
    from tfde_tpu.data.pipeline import Dataset

    ds = (
        Dataset.from_tensor_slices((tokens, seg))
        .shuffle(len(tokens), seed=seed)
        .repeat()
        .batch(batch_size, drop_remainder=True)
    )
    yield from iter(ds)
