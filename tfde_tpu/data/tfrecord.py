"""TFRecord file IO — the reference ecosystem's on-disk record format.

The reference's data layer rides tf.data, whose serialized-example format is
TFRecord: `<len u64le><masked-crc32c(len) u32le><data><masked-crc32c(data)>`
per record. The observability layer already hand-encodes this framing for
TensorBoard event files (observability/tensorboard.py — event files ARE
TFRecord files of Event protos); this module is the general reader/writer
over the same 13 lines of wire format, so datasets serialized by any
TensorFlow pipeline can feed this framework and vice versa.

Host-side by design (SURVEY.md §2b "tf.data C++ engine"): record IO is
sequential byte work for the host; parsed numpy batches go to the device
through the normal `data/pipeline.Dataset` path. All paths route through
`utils/fs`, so `gs://`/`memory://` URLs work like local files.
"""

from __future__ import annotations

import io
import struct
from typing import Callable, Iterable, Iterator, Optional, Sequence, Union

from tfde_tpu.observability.tensorboard import _masked_crc, _tfrecord
from tfde_tpu.utils import fs


class TFRecordWriter:
    """Write length-prefixed, crc-framed records to one file.

    Buffers in memory and writes on flush/close — object stores (the
    remote-working-dir contract, utils/fs) have no append, so the whole
    object is (re)written, same trade as the remote event writer.
    """

    def __init__(self, path: str):
        self._path = path
        self._buf = io.BytesIO()
        self._closed = False

    def write(self, record: bytes) -> None:
        if self._closed:
            raise ValueError(f"writer for {self._path} is closed")
        if not isinstance(record, (bytes, bytearray, memoryview)):
            # bytes(10) would silently write ten NUL bytes with valid CRCs
            raise TypeError(
                f"record must be bytes-like, got {type(record).__name__}"
            )
        self._buf.write(_tfrecord(bytes(record)))

    def flush(self) -> None:
        with fs.fs_open(self._path, "wb") as f:
            f.write(self._buf.getvalue())

    def close(self) -> None:
        if not self._closed:
            self.flush()
            self._closed = True

    def __enter__(self) -> "TFRecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_tfrecord(path: str, records: Iterable[bytes]) -> int:
    """Write all `records` to `path`; returns the record count."""
    n = 0
    with TFRecordWriter(path) as w:
        for r in records:
            w.write(r)
            n += 1
    return n


def read_tfrecord(
    path: str, verify_crc: bool = True
) -> Iterator[bytes]:
    """Yield each record's payload bytes from a TFRecord file, streaming —
    peak memory is O(record), not O(file).

    `verify_crc=True` (default) checks both the length and data CRCs and
    raises ValueError on corruption — truncated tails and bit flips fail
    loudly with the byte offset, never yield garbage.
    """
    with fs.fs_open(path, "rb") as f:
        off = 0
        while True:
            framing = f.read(12)
            if not framing:
                return  # clean EOF on a record boundary
            if len(framing) < 12:
                raise ValueError(
                    f"{path}: truncated record header at byte {off} "
                    f"({len(framing)} trailing bytes)"
                )
            header = framing[:8]
            (length,) = struct.unpack("<Q", header)
            (len_crc,) = struct.unpack("<I", framing[8:])
            if verify_crc and _masked_crc(header) != len_crc:
                raise ValueError(f"{path}: length crc mismatch at byte {off}")
            body = f.read(length + 4)
            if len(body) < length + 4:
                raise ValueError(
                    f"{path}: truncated record body at byte {off} "
                    f"(need {length + 4} bytes, have {len(body)})"
                )
            data = body[:length]
            (data_crc,) = struct.unpack("<I", body[length:])
            if verify_crc and _masked_crc(data) != data_crc:
                raise ValueError(f"{path}: data crc mismatch at byte {off}")
            yield data
            off += 12 + length + 4


def tfrecord_dataset(
    paths: Union[str, Sequence[str]],
    parse_fn: Optional[Callable[[bytes], object]] = None,
):
    """data/pipeline.Dataset over the records of one or more TFRecord files
    (files read in order, records in file order — apply `.shuffle()` on top,
    the tf.data convention). `parse_fn` maps payload bytes to the element
    (e.g. a numpy tuple); identity when None.

    Lazy like every pipeline node: files are opened and parsed per
    iteration, so a multi-GB corpus never materializes in host RAM and a
    consumer that takes two batches pays for two batches."""
    from tfde_tpu.data.pipeline import Dataset

    if isinstance(paths, str):
        paths = [paths]
    paths = list(paths)

    def it(epoch=0):
        for p in paths:
            for rec in read_tfrecord(p):
                el = parse_fn(rec) if parse_fn is not None else rec
                yield el if isinstance(el, tuple) else (el,)

    return Dataset(it, None)
