"""Masked-LM example construction — host-side, vectorized numpy.

The standard BERT recipe: select `mask_rate` of (non-special) positions;
of those, 80% become [MASK], 10% a random token, 10% keep the original.
Labels carry the original ids at selected positions and `ignore_id`
elsewhere; the loss (ops/losses.masked_lm_loss) averages CE over selected
positions only.

Host-side on purpose: masking is branch-heavy integer work that would
serialize on TPU scalar units; batches arrive at the device already masked,
exactly like the reference's host-side tf.data preprocessing
(distributed_with_keras.py:18-30).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

IGNORE_ID = -100  # conventional "not a target" label


@dataclasses.dataclass(frozen=True)
class MlmConfig:
    vocab_size: int
    mask_id: int
    mask_rate: float = 0.15
    mask_prob: float = 0.8    # -> [MASK]
    random_prob: float = 0.1  # -> uniform random token
    num_special: int = 0      # ids < num_special are never masked


def mask_tokens(
    tokens: np.ndarray, cfg: MlmConfig, rng: np.random.Generator
) -> Tuple[np.ndarray, np.ndarray]:
    """tokens [B,S] int -> (input_ids, labels), labels == IGNORE_ID where
    position is not a prediction target."""
    tokens = np.asarray(tokens)
    u = rng.random(tokens.shape)
    selected = (u < cfg.mask_rate) & (tokens >= cfg.num_special)
    # guarantee >= 1 target per example (degenerate rows skew the loss mean);
    # only eligible (non-special) positions may be forced — rows made
    # entirely of special/padding tokens are left target-free
    eligible = tokens >= cfg.num_special
    none = ~selected.any(axis=1) & eligible.any(axis=1)
    for row in np.flatnonzero(none):
        selected[row, rng.choice(np.flatnonzero(eligible[row]))] = True

    r = rng.random(tokens.shape)
    input_ids = tokens.copy()
    to_mask = selected & (r < cfg.mask_prob)
    to_random = selected & (r >= cfg.mask_prob) & (
        r < cfg.mask_prob + cfg.random_prob
    )
    input_ids[to_mask] = cfg.mask_id
    input_ids[to_random] = rng.integers(
        cfg.num_special, cfg.vocab_size, to_random.sum()
    )
    labels = np.where(selected, tokens, IGNORE_ID).astype(np.int32)
    return input_ids.astype(np.int32), labels
