"""Sequence packing for causal-LM training: variable-length documents
packed into fixed [B, S] rows with block-diagonal attention.

Padding each document to the max length wastes compute proportional to the
length variance; packing several documents per row recovers it — the
standard LM-pretraining input discipline. TPU-fit: shapes stay static (the
packed batch is an ordinary [B, S] int array plus a same-shaped segment-id
plane), the model's attention composes the segment mask with its causal
triangle (models/gpt.py `segment_ids=`), and the loss masks cross-document
boundary predictions. With rope positions the packed forward is EXACT per
document (rope attention depends only on relative in-segment position and
cross-segment pairs are masked — tests/test_packing.py pins packed logits
== solo logits).

Note: the segment mask routes attention to the reference einsum (the flash
kernel and the seq ring take causal/key-padding masks only) — packing is a
host-side throughput lever, not a kernel-side one.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

IGNORE_ID = -100


def pack_documents(
    docs: Sequence[np.ndarray],
    seq_len: int,
    pad_id: int = 0,
    max_open_rows: int = 1024,
) -> Tuple[np.ndarray, np.ndarray]:
    """Greedy first-fit packing over a BOUNDED pool of open rows: each
    document lands whole in the first open row with room (documents
    longer than seq_len split into seq_len chunks first). Returns
    (tokens [N, S], segment_ids [N, S]) with segment ids 1..k per row
    and 0 marking padding.

    Every input token appears exactly once, in order, within its segment
    (tested); rows are created on demand, so N adapts to the corpus.
    `max_open_rows` caps how many partially-filled rows stay candidates
    (oldest closes first past the cap; full rows close immediately), so
    packing stays O(pieces * max_open_rows) instead of quadratic at
    corpus scale, at a negligible density cost.
    """
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    pieces: List[np.ndarray] = []
    for d in docs:
        d = np.asarray(d)
        if d.ndim != 1:
            raise ValueError(
                f"each document must be a 1-D token array, got shape "
                f"{d.shape}"
            )
        if len(d) == 0:
            continue
        for start in range(0, len(d), seq_len):
            pieces.append(d[start:start + seq_len])

    rows: List[List[np.ndarray]] = []
    space: List[int] = []
    open_rows: List[int] = []  # indices into rows/space, oldest first
    for p in pieces:
        placed = False
        for j, i in enumerate(open_rows):
            if len(p) <= space[i]:
                rows[i].append(p)
                space[i] -= len(p)
                if space[i] == 0:
                    open_rows.pop(j)
                placed = True
                break
        if not placed:
            rows.append([p])
            space.append(seq_len - len(p))
            if space[-1] > 0:
                open_rows.append(len(rows) - 1)
                if len(open_rows) > max_open_rows:
                    open_rows.pop(0)

    n = max(len(rows), 1)
    tokens = np.full((n, seq_len), pad_id, dtype=np.int32)
    segment_ids = np.zeros((n, seq_len), dtype=np.int32)
    for i, row in enumerate(rows):
        at = 0
        for seg, p in enumerate(row, start=1):
            tokens[i, at:at + len(p)] = p
            segment_ids[i, at:at + len(p)] = seg
            at += len(p)
    return tokens, segment_ids


def valid_targets(segment_ids):
    """[B, S-1] bool: position i+1 is a valid next-token target of
    position i — same segment, not padding. The ONE definition of the
    boundary rule, shared by the host-side `packed_labels` and the
    on-device `packed_next_token_loss` (numpy and jnp arrays both
    accepted — only elementwise ops are used)."""
    seg = segment_ids
    return (seg[:, 1:] > 0) & (seg[:, 1:] == seg[:, :-1])


def packed_labels(tokens: np.ndarray, segment_ids: np.ndarray,
                  ignore_id: int = IGNORE_ID) -> np.ndarray:
    """Next-token labels for a packed batch, aligned to the shifted loss
    (label[i] is the target of position i-1): positions whose PREDICTION
    would cross a document boundary — the first token of every segment
    and all padding — are `ignore_id`."""
    tokens = np.asarray(tokens)
    seg = np.asarray(segment_ids)
    labels = tokens.copy().astype(np.int32)
    valid = np.zeros_like(seg, dtype=bool)
    valid[:, 1:] = valid_targets(seg)
    labels[~valid] = ignore_id
    return labels


def packed_next_token_loss(state, params, batch, rng):
    """(loss, metrics) for make_custom_train_step over packed batches:
    batch = (tokens, segment_ids). Shifted CE over in-segment positions
    only (cross-boundary and padding predictions are masked), with
    `grad_weight` carrying the target count so gradient accumulation
    reproduces the exact full-batch update on unevenly-packed
    microbatches (training/step.py). Applies with mutable=["losses"] so
    a routed (MoE) GPT's sown balance losses join the objective here
    exactly as in next_token_loss."""
    from tfde_tpu.ops.losses import masked_lm_loss
    from tfde_tpu.training.step import sown_losses_by_name

    tokens, segment_ids = batch
    logits, mutated = state.apply_fn(
        {"params": params}, tokens, train=True, segment_ids=segment_ids,
        rngs={"dropout": rng}, mutable=["losses"],
    )
    seg = segment_ids.astype(jnp.int32)
    labels = tokens[:, 1:].astype(jnp.int32)
    valid = valid_targets(seg)
    labels = jnp.where(valid, labels, IGNORE_ID)
    loss, acc = masked_lm_loss(logits[:, :-1], labels)
    n_targets = jnp.sum(valid.astype(jnp.float32))
    metrics = {"packed_accuracy": acc, "grad_weight": n_targets}
    for name, total in sown_losses_by_name(
            mutated.get("losses", {})).items():
        loss = loss + total
        metrics[name] = total
    return loss, metrics
