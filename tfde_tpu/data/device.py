"""Host->device feed: sharded device placement with double-buffered prefetch.

The analog of tf.data's device prefetch plus the distribution-strategy input
splitting (SURVEY.md §2b row 3). Batches come off the host pipeline as numpy;
we place each as a *global* jax.Array laid out by the mesh's batch sharding
and keep `buffer_size` batches in flight so the host copy overlaps the
device step — the overlap that the ≥90 % scaling-efficiency target depends on
(SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import collections
import time
from typing import Iterable, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfde_tpu.data.pipeline import AutoShardPolicy


def local_slice_for_process(global_batch: int) -> Tuple[int, slice]:
    """(per-host batch, this host's slice of a global batch).

    Global-batch accounting per distributed_with_keras.py:13-15: the global
    batch divides evenly across processes; under OFF each host materializes
    the full global batch and takes its slice (dwk:54-57), under DATA each
    host produces only its per-host portion.
    """
    n = jax.process_count()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by {n} processes")
    per = global_batch // n
    i = jax.process_index()
    return per, slice(i * per, (i + 1) * per)


def _to_global(batch, sharding: NamedSharding, policy: AutoShardPolicy):
    def place(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        if policy is AutoShardPolicy.OFF:
            _, sl = local_slice_for_process(x.shape[0])
            x = x[sl]
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(place, batch)


def device_prefetch(
    batches: Iterable,
    mesh: Mesh,
    spec: Optional[P] = None,
    buffer_size: int = 2,
    policy: AutoShardPolicy = AutoShardPolicy.DATA,
    background: bool = False,
    wait_metric: Optional[str] = None,
) -> Iterator:
    """Yield global device arrays, keeping `buffer_size` transfers in flight.

    `jax.device_put` is async: enqueueing the next batch's transfer before the
    consumer blocks on the current step gives copy/compute overlap (the
    `prefetch(100)` capability of mnist_keras:145 plus `experimental_prefetch_
    to_device`, without the 100-deep host queue — device HBM holds the window).

    `background=True` moves the host-batch pull AND the device_put into a
    worker thread (a `buffer_size`-deep queue hands finished device arrays
    to the consumer). Use when either blocks the calling thread — a host
    pipeline with real per-batch work, or a link whose device_put is
    effectively synchronous (a high-latency tunnel): the transfer then
    overlaps the device step even though the consumer never returns to
    Python between steps. Same stream, same order; worker exceptions
    re-raise in the consumer.

    `wait_metric` names an observability histogram (e.g. "train/data_wait")
    that records the seconds the CONSUMER blocks per `next()` — the host
    pull + device_put inline, the queue wait in background mode. This is
    the input-boundness signal goodput accounting classifies as data_wait;
    None (the default) records nothing.
    """
    if wait_metric is None:
        def _rec(dt: float) -> None:
            pass
    else:
        from tfde_tpu.observability import spans

        def _rec(dt: float, _name=wait_metric) -> None:
            spans.record(_name, dt)
    if spec is None:
        from tfde_tpu.parallel.sharding import batch_spec

        spec = batch_spec(mesh)
    sharding = NamedSharding(mesh, spec)

    if background:
        import queue as _queue
        import threading

        q: "_queue.Queue" = _queue.Queue(maxsize=max(1, buffer_size))
        _END = object()

        class _Raise:  # unambiguous error envelope (a batch is never one)
            def __init__(self, e):
                self.e = e

        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up when the consumer is gone — a
            # consumer breaking out of its loop early must not leave the
            # worker blocked forever pinning device arrays
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def worker():
            try:
                for b in batches:
                    if not put(_to_global(b, sharding, policy)):
                        return
                put(_END)
            except BaseException as e:
                put(_Raise(e))

        threading.Thread(target=worker, daemon=True,
                         name="tfde-device-prefetch").start()

        empty_exc = _queue.Empty  # bind the class in the closure: at
        # interpreter shutdown a GC'd generator's finally can run after
        # module teardown has nulled `queue.Empty`

        def gen():
            try:
                while True:
                    t0 = time.perf_counter()
                    item = q.get()
                    _rec(time.perf_counter() - t0)
                    if item is _END:
                        return
                    if isinstance(item, _Raise):
                        raise item.e
                    yield item
            finally:
                # generator close/GC: release the worker and drop any
                # buffered device arrays
                stop.set()
                try:
                    while True:
                        q.get_nowait()
                except empty_exc:
                    pass

        return gen()

    def gen_inline():
        # time between yields IS the consumer's blocking wait in next():
        # the priming fill is charged to the first draw, each refill to
        # the draw it delays
        buf: collections.deque = collections.deque()
        it = iter(batches)
        t0 = time.perf_counter()
        try:
            while len(buf) < max(1, buffer_size):
                buf.append(_to_global(next(it), sharding, policy))
        except StopIteration:
            pass
        while buf:
            out = buf.popleft()
            try:
                buf.append(_to_global(next(it), sharding, policy))
            except StopIteration:
                pass
            _rec(time.perf_counter() - t0)
            yield out
            t0 = time.perf_counter()

    return gen_inline()


def device_resident_feed(
    arrays,
    mesh: Mesh,
    global_batch: int,
    seed: int = 0,
    spec: Optional[P] = None,
    drop_remainder: bool = True,
):
    """Fully ON-DEVICE input pipeline for datasets that fit in HBM: stage
    the arrays once, then every batch is a device-side gather — ZERO
    per-step host->device traffic, the terminal answer to an input-bound
    link (bench.py measured the MNIST e2e path 8.7x off the compute path
    through the axon tunnel, with per-batch transfer as the attributed
    cost).

    Semantics match `Dataset.from_tensor_slices(arrays).shuffle(n, seed)
    .repeat().batch(global_batch, drop_remainder=True)`: a fresh
    Fisher-Yates permutation per epoch (derived on device from `seed` and
    the epoch index), batches crossing epoch boundaries never (each epoch
    truncates to a whole number of batches when drop_remainder — the
    in-memory analog of the streaming loader's per-epoch windows).

    Returns `feed(step) -> batch` — a jitted function of the step index;
    call it with the training step counter. The gather output is sharded
    by the mesh's batch spec, so it drops into the train step exactly
    like a `device_prefetch` batch.
    """
    import jax.numpy as jnp

    if spec is None:
        from tfde_tpu.parallel.sharding import batch_spec

        spec = batch_spec(mesh)
    sharding = NamedSharding(mesh, spec)
    arrays = tuple(np.ascontiguousarray(a) for a in arrays)
    n = arrays[0].shape[0]
    if any(a.shape[0] != n for a in arrays):
        raise ValueError("all arrays must share the leading dimension")
    if not drop_remainder and n % global_batch:
        raise ValueError(
            "device_resident_feed streams whole batches only; use "
            "drop_remainder=True (or a divisible dataset) — a trailing "
            "partial batch would change the compiled shape"
        )
    per_epoch = n // global_batch
    if per_epoch < 1:
        raise ValueError(
            f"global_batch {global_batch} exceeds the dataset size {n}"
        )
    # replicated residency: the gather needs arbitrary rows on every
    # shard's output row, so the source stays whole on each device (the
    # fits-in-HBM contract this feed is for; shard the OUTPUT, not the
    # source)
    dev = tuple(
        jax.device_put(a, NamedSharding(mesh, P())) for a in arrays
    )

    @jax.jit
    def feed(step):
        epoch = step // per_epoch
        within = step % per_epoch
        perm = jax.random.permutation(
            jax.random.fold_in(jax.random.key(seed), epoch), n
        )
        idx = jax.lax.dynamic_slice_in_dim(
            perm, within * global_batch, global_batch
        )
        out = tuple(
            jax.lax.with_sharding_constraint(jnp.take(a, idx, axis=0),
                                             sharding)
            for a in dev
        )
        return out

    return feed
