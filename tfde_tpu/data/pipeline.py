"""Composable host-side input pipeline (the tf.data analog).

Reproduces the observable semantics the reference relies on:

- `from_tensor_slices` + `.shuffle(1000).repeat().batch(B).prefetch(100)` for
  training and plain `.batch(B)` for eval/predict
  (mnist_keras_distributed.py:123-148, duplicated tf2_mnist:38-63);
- `.map(scale).cache().shuffle(10000)` then global-batching
  (distributed_with_keras.py:18-30,54);
- `AutoShardPolicy` OFF vs DATA (distributed_with_keras.py:55-57): under DATA
  each host reads its own example shard; under OFF every host iterates the
  identical stream and slices its chips' portion out of each *global* batch —
  exactly the reference's global-batch accounting (dwk:13-15).

Semantics notes (tf.data-compatible):
- `repeat().batch()` batches across epoch boundaries — never a per-epoch
  short batch (keeps jit shapes static).
- seeded `shuffle` reshuffles every epoch (reshuffle-each-iteration): epoch k
  uses seed+k; a fresh iterator restarts the same deterministic sequence.
- exceptions raised inside the pipeline (map fns, sources) propagate to the
  consumer, including through `prefetch`'s background thread.

Design: nodes are iterator factories over numpy, threaded by an *epoch index*
(`make_iter(epoch)`) so `repeat` can drive per-epoch reshuffling upstream.
`batch` is vectorized — one permutation + one fancy-indexed gather per batch —
whenever the upstream chain is slice-preserving (source, elementwise map,
cache, full-buffer shuffle, repeat); otherwise it falls back to the exact
per-element path. The native C++ loader (tfde_tpu/native) slots in as an
alternative source with the same element contract.
"""

from __future__ import annotations

import dataclasses
import enum
import queue
import threading
from typing import Any, Callable, Iterator, Optional, Tuple

import numpy as np

Element = Tuple[np.ndarray, ...]

_NO_SHUFFLE = object()


@dataclasses.dataclass
class _FastPath:
    """State for the vectorized batch path: sliceable arrays + pending
    shuffle/repeat transformations that commute with slicing."""

    arrays_thunk: Callable[[], Tuple[np.ndarray, ...]]  # lazy (deferred maps)
    n: int
    perm_seed: Any = _NO_SHUFFLE  # _NO_SHUFFLE | None | int
    repeat: Optional[int] = 1  # None = infinite

    def evolved(self, **kw) -> "_FastPath":
        return dataclasses.replace(self, **kw)


class Dataset:
    """A lazily-evaluated pipeline; each op returns a new Dataset."""

    def __init__(
        self,
        make_iter: Callable[..., Iterator[Element]],
        size: Optional[int],
        fast: Optional[_FastPath] = None,
    ):
        # make_iter accepts an optional epoch index (for per-epoch reshuffle).
        self._make_iter = make_iter
        self._size = size  # elements per iteration where known; None unknown/infinite
        self._fast = fast

    def _iter_epoch(self, epoch: int = 0) -> Iterator[Element]:
        try:
            return self._make_iter(epoch)
        except TypeError:
            return self._make_iter()

    # -- sources -------------------------------------------------------------
    @staticmethod
    def from_tensor_slices(arrays: Any) -> "Dataset":
        """Source over the leading axis of one array or a tuple of arrays
        (mnist_keras:142)."""
        if not isinstance(arrays, (tuple, list)):
            arrays = (arrays,)
        arrays = tuple(np.asarray(a) for a in arrays)
        n = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n:
                raise ValueError("all arrays must share the leading dimension")

        def it(epoch=0):
            for i in range(n):
                yield tuple(a[i] for a in arrays)

        return Dataset(it, n, fast=_FastPath(lambda: arrays, n))

    # -- transformations -----------------------------------------------------
    def map(self, fn: Callable[..., Any]) -> "Dataset":
        def it(epoch=0):
            for el in self._iter_epoch(epoch):
                out = fn(*el)
                yield out if isinstance(out, tuple) else (out,)

        fast = None
        if self._fast is not None:
            parent = self._fast

            def mapped_thunk():
                src = parent.arrays_thunk()
                mapped = fn(*src)
                mapped = mapped if isinstance(mapped, tuple) else (mapped,)
                mapped = tuple(np.asarray(m) for m in mapped)
                # A whole-array map equals the per-element map only for
                # elementwise/broadcasting fns (the reference's are,
                # dwk:20-23). Verify on element 0; reductions or
                # shape-dependent fns fail and void the fast path.
                el0 = fn(*(a[0] for a in src))
                el0 = el0 if isinstance(el0, tuple) else (el0,)
                ok = len(mapped) == len(el0) and all(
                    m.shape[0] == src[0].shape[0]
                    and np.allclose(m[0], np.asarray(e), equal_nan=True)
                    for m, e in zip(mapped, el0)
                )
                return mapped if ok else None

            fast = parent.evolved(arrays_thunk=_memo(mapped_thunk))
        return Dataset(it, self._size, fast=fast)

    def cache(self) -> "Dataset":
        """Materialize once on first full pass (dwk:30)."""
        store: list[Element] = []
        done = threading.Event()

        def it(epoch=0):
            if done.is_set():
                yield from store
                return
            buf = []
            for el in self._iter_epoch(epoch):
                buf.append(el)
                yield el
            store[:] = buf
            done.set()

        return Dataset(it, self._size, fast=self._fast)

    def shuffle(self, buffer_size: int, seed: Optional[int] = None) -> "Dataset":
        """Windowed buffer shuffle, tf.data semantics (mnist_keras:144):
        reshuffles each epoch; with a seed the epoch sequence is deterministic.
        """
        def it(epoch=0):
            rng = np.random.default_rng(None if seed is None else seed + epoch)
            buf: list[Element] = []
            for el in self._iter_epoch(epoch):
                if len(buf) < buffer_size:
                    buf.append(el)
                    continue
                j = int(rng.integers(buffer_size))
                out = buf[j]
                buf[j] = el
                yield out
            rng.shuffle(buf)
            yield from buf

        fast = None
        if self._fast is not None and self._size is not None and buffer_size >= self._size:
            # Full-buffer shuffle == a fresh permutation per epoch.
            fast = self._fast.evolved(perm_seed=seed)
        return Dataset(it, self._size, fast=fast)

    def repeat(self, count: Optional[int] = None) -> "Dataset":
        def it(epoch=0):
            n = 0
            while count is None or n < count:
                yield from self._iter_epoch(n)
                n += 1

        size = None if (count is None or self._size is None) else self._size * count
        fast = self._fast.evolved(repeat=count) if self._fast is not None else None
        return Dataset(it, size, fast=fast)

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """Every num_shards-th element — AutoShardPolicy.DATA per-host shard."""
        def it(epoch=0):
            for i, el in enumerate(self._iter_epoch(epoch)):
                if i % num_shards == index:
                    yield el

        size = None if self._size is None else (self._size - index + num_shards - 1) // num_shards
        return Dataset(it, size)

    def batch(self, batch_size: int, drop_remainder: bool = False) -> "Dataset":
        """Stack consecutive elements; vectorized when the chain allows."""
        if self._fast is not None:
            arrays = self._fast.arrays_thunk()  # None if a map was non-elementwise
            if arrays is not None:
                return _VectorBatched(arrays, batch_size, drop_remainder, self._fast)

        def it(epoch=0):
            buf: list[Element] = []
            for el in self._iter_epoch(epoch):
                buf.append(el)
                if len(buf) == batch_size:
                    yield tuple(np.stack(c) for c in zip(*buf))
                    buf = []
            if buf and not drop_remainder:
                yield tuple(np.stack(c) for c in zip(*buf))

        size = None
        if self._size is not None:
            size = self._size // batch_size if drop_remainder else -(-self._size // batch_size)
        return Dataset(it, size)

    def prefetch(self, buffer_size: int = 2) -> "Dataset":
        """Background-thread prefetch (mnist_keras:145). Upstream exceptions
        propagate to the consumer."""
        def it(epoch=0):
            q: queue.Queue = queue.Queue(maxsize=max(1, buffer_size))
            stop = object()
            err: list[BaseException] = []

            def worker():
                try:
                    for el in self._iter_epoch(epoch):
                        q.put(el)
                except BaseException as e:  # propagate, don't truncate
                    err.append(e)
                finally:
                    q.put(stop)

            t = threading.Thread(target=worker, daemon=True)
            t.start()
            while True:
                el = q.get()
                if el is stop:
                    if err:
                        raise err[0]
                    return
                yield el

        return Dataset(it, self._size)

    # -- consumption ---------------------------------------------------------
    def __iter__(self) -> Iterator[Element]:
        return self._iter_epoch(0)

    def __len__(self) -> int:
        if self._size is None:
            raise TypeError("dataset size unknown (infinite or un-counted)")
        return self._size

    @property
    def size(self) -> Optional[int]:
        return self._size


def _memo(thunk):
    cell = []

    def memoized():
        if not cell:
            cell.append(thunk())
        return cell[0]

    return memoized


class _VectorBatched(Dataset):
    """Vectorized shuffle+repeat+batch over sliceable arrays.

    Host hot path: one `rng.permutation` per epoch and one fancy-indexed
    gather per batch — no per-example Python. Batches run across epoch
    boundaries (tf.data repeat().batch() semantics)."""

    def __init__(self, arrays, batch_size, drop_remainder, fast: _FastPath):
        self._arrays = arrays
        self._bs = batch_size
        self._drop = drop_remainder
        self._seed = fast.perm_seed
        self._rep = fast.repeat  # None = infinite
        self._n = fast.n
        total = None if fast.repeat is None else fast.n * fast.repeat
        size = None
        if total is not None:
            size = total // batch_size if drop_remainder else -(-total // batch_size)
        super().__init__(self._iter, size)

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        if self._seed is _NO_SHUFFLE:
            return np.arange(self._n)
        rng = np.random.default_rng(None if self._seed is None else self._seed + epoch)
        return rng.permutation(self._n)

    def _iter(self, _epoch: int = 0):
        epoch, carry = 0, np.empty((0,), np.int64)
        while self._rep is None or epoch < self._rep:
            idx = np.concatenate([carry, self._epoch_indices(epoch)])
            stop = len(idx) - (len(idx) % self._bs)
            for s in range(0, stop, self._bs):
                sel = idx[s : s + self._bs]
                yield tuple(a[sel] for a in self._arrays)
            carry = idx[stop:]
            epoch += 1
        if len(carry) and not self._drop:
            yield tuple(a[carry] for a in self._arrays)


class AutoShardPolicy(enum.Enum):
    """Input-sharding policy across hosts (distributed_with_keras.py:55-57).

    OFF: every host iterates the identical full stream and slices its own
    portion out of each global batch. DATA: each host reads every
    num_shards-th example (its own shard)."""

    OFF = "off"
    DATA = "data"
