"""Streaming file-backed input: TFRecord shards -> windowed shuffle ->
native gather ring -> device.

Closes the file-to-chip gap (VERDICT r3 next-round #6): the C++ loader
(native/loader.cc) gathers from in-memory arrays, and `tfrecord_dataset`
(data/tfrecord.py) streams records but batches in Python — neither alone is
the ImageNet-scale path, where the dataset does not fit host RAM and the
per-batch gather must not run under the GIL. This module is the composition
the reference gets from tf.data's C++ engine (`TFRecordDataset -> shuffle ->
batch -> prefetch`, SURVEY.md §2b row 3):

- A READER thread decodes records from this host's file shards into fixed
  [window, ...] numpy buffers (CRC-checked, utils/fs so gs:// works), with
  a 1-deep queue for backpressure: peak host memory is O(2 windows), never
  O(dataset).
- Each filled window feeds a fresh native gather ring
  (`NativeBatchLoader`: GIL-free per-window permutation + memcpy gather +
  prefetch depth), while the reader is already filling the next window —
  decode and gather overlap. Without a toolchain the gather degrades to
  numpy fancy indexing, same semantics.
- Shuffle is WINDOWED (buffer = `window` rows, the
  `tf.data.shuffle(buffer_size)` approximation —
  `/root/reference/mnist_keras_distributed.py:144`,
  `distributed_with_keras.py:29`), seeded, and PER-EPOCH: file order
  reshuffles each epoch and a window never spans an epoch boundary, so
  every epoch's records precede the next epoch's, matching
  `shuffle(B).repeat()` ordering. Up to batch-1 tail rows of an epoch
  join the next epoch's first window so batches stay full across the
  boundary — the `repeat().batch()` batch-crossing contract
  (data/pipeline.py has the same semantics in-memory).
- Multi-host sharding is by FILE, round-robin (the tf.data
  `AutoShardPolicy.FILE` analog): host h of H reads files h, h+H, ... —
  no host reads bytes destined for another.

The yielded numpy batches go to the device through the normal
`data.device.device_prefetch` double-buffering, so the chip never waits on
the host for datasets of any size.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Sequence, Tuple, Union

import numpy as np

from tfde_tpu.data.tfrecord import read_tfrecord


def shard_files(
    paths: Sequence[str], host_index: int, host_count: int
) -> list:
    """Round-robin file assignment (AutoShardPolicy.FILE semantics): host
    h takes files h, h+H, h+2H, ... Raises when hosts would starve —
    fewer files than hosts means file-level sharding cannot feed every
    host; re-shard the dataset or use record-level `Dataset.shard`."""
    if not 0 <= host_index < host_count:
        raise ValueError(
            f"host_index {host_index} not in [0, {host_count})"
        )
    if len(paths) < host_count:
        raise ValueError(
            f"{len(paths)} files cannot file-shard across {host_count} "
            f"hosts — every host needs at least one file (write more "
            f"shards, or use record-level Dataset.shard on a "
            f"tfrecord_dataset)"
        )
    return list(paths[host_index::host_count])


class StreamingTFRecordLoader:
    """shuffle/repeat/batch over TFRecord shards that never materializes
    the dataset in memory (module docstring has the architecture).

    paths: this host's shard files (apply `shard_files` first in
    multi-host jobs, or pass host_index/host_count to do it here).
    parse_fn: bytes -> tuple of fixed-shape numpy values (row contract;
    shapes/dtypes are pinned by the first record and enforced after).
    window: shuffle-buffer rows resident at once (2 windows peak).
    repeat: None = infinite epochs (the training default), k = k passes.

    Yields tuples of numpy batch arrays; the final partial batch of the
    final epoch is dropped iff drop_remainder. Iteration is
    single-consumer; `close()` (or GC) stops the reader thread.
    """

    def __init__(
        self,
        paths: Union[str, Sequence[str]],
        parse_fn: Callable[[bytes], tuple],
        batch_size: int,
        window: int = 65536,
        shuffle: bool = True,
        seed: int = 0,
        repeat: Optional[int] = None,
        drop_remainder: bool = False,
        host_index: Optional[int] = None,
        host_count: Optional[int] = None,
        num_threads: int = 2,
        depth: int = 4,
        # True (default): yielded arrays are owned. False hands out views
        # of the native ring's slots, valid only until the next iteration —
        # NOT safe under device_prefetch, whose async device_put still
        # reads the host buffer after the iterator advances (measured: NaN
        # batches). Only disable for a strictly synchronous consumer.
        copy: bool = True,
    ):
        if isinstance(paths, str):
            paths = [paths]
        paths = list(paths)
        if not paths:
            raise ValueError("need at least one TFRecord file")
        if (host_index is None) != (host_count is None):
            raise ValueError(
                "pass host_index and host_count together (or neither)"
            )
        if host_index is not None:
            paths = shard_files(paths, host_index, host_count)
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if window < batch_size:
            raise ValueError(
                f"window ({window}) must be >= batch_size ({batch_size}) "
                f"— a window is the shuffle buffer batches draw from"
            )
        if repeat is not None and repeat < 0:
            raise ValueError(f"repeat must be None or >= 0, got {repeat}")
        self._paths = paths
        self._parse = parse_fn
        self._batch = int(batch_size)
        self._window = int(window)
        self._shuffle = bool(shuffle)
        self._seed = int(seed)
        self._repeat = repeat
        self._drop_remainder = bool(drop_remainder)
        self._native_kw = dict(num_threads=num_threads, depth=depth,
                               copy=copy)
        # (bufs, count, is_last) | ('error', exc) | None = reader done
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._reader, name="tfde-stream-reader", daemon=True
        )
        self._thread.start()
        self._inner = None  # gather engine over the current window
        self._window_idx = 0
        self._done = False
        self._leftover = None  # rows spilling across a window boundary

    # -- reader thread ------------------------------------------------------
    _EPOCH_END = object()

    def _rows(self):
        epoch = 0
        while self._repeat is None or epoch < self._repeat:
            paths = self._paths
            if self._shuffle:
                order = np.random.default_rng(
                    (self._seed, epoch)
                ).permutation(len(paths))
                paths = [self._paths[i] for i in order]
            n_epoch = 0
            for p in paths:
                for rec in read_tfrecord(p):
                    if self._stop.is_set():
                        return
                    n_epoch += 1
                    yield self._parse(rec)
            if n_epoch == 0:
                return  # empty dataset: repeating it forever yields nothing
            epoch += 1
            # windows must not span epochs: shuffle is per-epoch
            # (tf.data `shuffle(B).repeat()` order — all of epoch N
            # precedes epoch N+1), so the reader flushes at the boundary
            yield self._EPOCH_END

    def _reader(self):
        try:
            rows = self._rows()
            first = next(rows, None)
            if first is None:
                self._q.put(None)
                return
            first = tuple(np.asarray(v) for v in first)
            shapes = [v.shape for v in first]
            dtypes = [v.dtype for v in first]
            carry = [first]
            exhausted = False
            while not exhausted and not self._stop.is_set():
                bufs = [
                    np.empty((self._window,) + sh, dt)
                    for sh, dt in zip(shapes, dtypes)
                ]
                count = 0
                for row in carry:
                    for b, v in zip(bufs, row):
                        b[count] = v
                    count += 1
                carry = []
                while count < self._window:
                    row = next(rows, None)
                    if row is None:
                        exhausted = True
                        break
                    if row is self._EPOCH_END:
                        break  # flush: a window never spans epochs
                    row = tuple(np.asarray(v) for v in row)
                    for v, sh, dt in zip(row, shapes, dtypes):
                        if v.shape != sh or v.dtype != dt:
                            raise ValueError(
                                f"record {count} of window "
                                f"{self._window_idx} has shape/dtype "
                                f"{v.shape}/{v.dtype}, expected {sh}/{dt} "
                                f"— parse_fn must yield fixed-shape rows"
                            )
                    for b, v in zip(bufs, row):
                        b[count] = v
                    count += 1
                # no tail trimming: each window's gather emits a short
                # final chunk and the CONSUMER re-batches across windows —
                # boundary rows therefore precede the next window's (the
                # tf.data `shuffle(B).repeat().batch()` ordering law:
                # every epoch-N record is emitted before any epoch-N+1
                # record; tests/test_tfdata_parity.py asserts it)
                if count:
                    self._q.put((bufs, count, exhausted))
            self._q.put(None)
        except BaseException as e:  # surface in the consumer, not the log
            self._q.put(("error", e))

    # -- consumer -----------------------------------------------------------
    def _next_window(self):
        item = self._q.get()
        if item is None:
            self._done = True
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and item[0] == "error":
            self._done = True
            raise item[1]
        bufs, count, is_last = item
        views = [b[:count] for b in bufs]
        seed = np.random.default_rng(
            (self._seed, 7, self._window_idx)
        ).integers(0, 2**63)
        self._window_idx += 1
        from tfde_tpu import native

        # engines always emit the window's short final chunk
        # (drop_remainder=False): __next__ re-batches across windows, so
        # boundary rows keep their position in the stream
        if native.available():
            self._inner = native.NativeBatchLoader(
                views, self._batch, shuffle=self._shuffle, seed=int(seed),
                repeat=1, drop_remainder=False, **self._native_kw,
            )
        else:
            self._inner = self._numpy_window(views, count, int(seed))

    def _numpy_window(self, views, count, seed):
        order = (np.random.default_rng(seed).permutation(count)
                 if self._shuffle else np.arange(count))

        def gen():
            for start in range(0, count, self._batch):
                idx = order[start : start + self._batch]
                yield tuple(v[idx] for v in views)

        return gen()

    def __iter__(self):
        return self

    def _pull_chunk(self):
        """Next (possibly short) chunk from the window engines."""
        while True:
            if self._inner is None:
                self._next_window()  # raises StopIteration at end
            try:
                return next(self._inner)
            except StopIteration:
                self._inner = None

    def __next__(self) -> Tuple[np.ndarray, ...]:
        if self._done:
            raise StopIteration
        parts = [self._leftover] if self._leftover is not None else []
        have = parts[0][0].shape[0] if parts else 0
        while have < self._batch:
            try:
                chunk = self._pull_chunk()
            except StopIteration:
                if parts and not self._drop_remainder:
                    self._leftover = None
                    self._done = True
                    return tuple(np.concatenate(c, axis=0) if len(parts) > 1
                                 else c[0]
                                 for c in zip(*parts))
                self._done = True
                raise
            parts.append(chunk)
            have += chunk[0].shape[0]
        merged = tuple(
            np.concatenate(c, axis=0) if len(parts) > 1 else c[0]
            for c in zip(*parts)
        )
        if have > self._batch:
            # copy the spill: under copy=False it would otherwise alias a
            # ring slot that the next _pull_chunk recycles
            self._leftover = tuple(a[self._batch :].copy() for a in merged)
            merged = tuple(a[: self._batch] for a in merged)
        else:
            self._leftover = None
        return merged

    def close(self) -> None:
        self._stop.set()
        inner, self._inner = self._inner, None
        if inner is not None and hasattr(inner, "close"):
            inner.close()
        # drain until the reader exits: it may be blocked in q.put (full
        # queue) and needs one more drain after waking to place its final
        # sentinel; bounded loop so close never hangs on a wedged thread
        for _ in range(1000):
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            if not self._thread.is_alive():
                break
            self._thread.join(0.01)
        self._done = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
