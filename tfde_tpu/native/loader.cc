// Native host data loader — the C++ analog of tf.data's C++ iterator/prefetch
// engine (SURVEY.md §2b row 3: the reference's input pipelines delegate
// shuffle/repeat/batch/prefetch to TensorFlow's C++ runtime; this supplies the
// same capability for the TPU-native framework).
//
// Design: N source arrays share a leading dimension. A pool of worker threads
// fills a ring of `depth` batch slots; batch b always lands in slot b % depth,
// so the consumer sees batches in deterministic order regardless of thread
// interleaving. Per-epoch Fisher-Yates shuffle (splitmix64 PRNG, seed+epoch)
// with tf.data `repeat().batch()` semantics: batches run across epoch
// boundaries, no per-epoch short batch. Row gather is memcpy — the pipeline
// is memory-bandwidth-bound, exactly what the GIL-free threads buy over the
// numpy fancy-index path.
//
// C ABI only (consumed via ctypes from tfde_tpu/native/__init__.py).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t state;
  explicit SplitMix64(uint64_t seed) : state(seed) {}
  uint64_t next() {
    uint64_t z = (state += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }
  // unbiased bounded draw (Lemire)
  uint64_t bounded(uint64_t n) {
    uint64_t x = next();
    __uint128_t m = (__uint128_t)x * (__uint128_t)n;
    uint64_t l = (uint64_t)m;
    if (l < n) {
      uint64_t t = -n % n;
      while (l < t) {
        x = next();
        m = (__uint128_t)x * (__uint128_t)n;
        l = (uint64_t)m;
      }
    }
    return (uint64_t)(m >> 64);
  }
};

struct Slot {
  std::vector<std::vector<uint8_t>> buffers;  // one per array
  int64_t batch_id = -1;     // batch currently occupying the slot
  int64_t consumed_id = -1;  // last batch fully drained from this slot
  int64_t rows = 0;          // rows actually filled (short final batch)
  bool ready = false;
  std::mutex mu;
  std::condition_variable cv;
};

struct Loader {
  // immutable config
  std::vector<const uint8_t*> data;
  std::vector<size_t> row_bytes;
  int64_t n_rows;
  int64_t batch;
  bool drop_remainder;
  bool shuffle;
  uint64_t seed;
  int64_t repeat;  // -1 = infinite
  int64_t total_batches;  // -1 = infinite

  // permutation cache (guarded by perm_mu): epoch -> shared permutation.
  // shared_ptr so a worker holding an epoch's permutation is immune to
  // concurrent eviction by workers on later epochs.
  std::mutex perm_mu;
  std::map<int64_t, std::shared_ptr<const std::vector<int64_t>>> perms;

  std::vector<Slot> slots;
  std::atomic<int64_t> next_batch{0};  // claimed by workers
  int64_t consumed = 0;                // consumer cursor
  std::atomic<bool> stop{false};
  std::atomic<int> active_next{0};  // consumers currently inside next()
  std::vector<std::thread> workers;

  std::shared_ptr<const std::vector<int64_t>> permutation_for(int64_t epoch) {
    std::lock_guard<std::mutex> g(perm_mu);
    auto it = perms.find(epoch);
    if (it != perms.end()) return it->second;
    auto p = std::make_shared<std::vector<int64_t>>(n_rows);
    for (int64_t i = 0; i < n_rows; ++i) (*p)[i] = i;
    SplitMix64 rng(seed + (uint64_t)epoch);
    for (int64_t i = n_rows - 1; i > 0; --i) {
      int64_t j = (int64_t)rng.bounded((uint64_t)i + 1);
      std::swap((*p)[i], (*p)[j]);
    }
    perms[epoch] = p;
    // bound the cache: epochs more than a prefetch-window behind are dead
    while (perms.size() > 8) perms.erase(perms.begin());
    return perms[epoch];
  }

  void fill(Slot& slot, int64_t b) {
    int64_t start = b * batch;
    int64_t limit = (repeat < 0) ? INT64_MAX : repeat * n_rows;
    int64_t end = std::min(start + batch, limit);
    int64_t rows = end - start;
    // resolve source rows once (a batch may span many epochs when
    // batch > n_rows); the permutation fetch locks only on epoch change
    std::vector<int64_t> src_rows((size_t)rows);
    if (shuffle) {
      int64_t cur_epoch = -1;
      std::shared_ptr<const std::vector<int64_t>> perm;
      for (int64_t r = 0; r < rows; ++r) {
        int64_t g = start + r;
        int64_t epoch = g / n_rows;
        if (epoch != cur_epoch) {
          perm = permutation_for(epoch);
          cur_epoch = epoch;
        }
        src_rows[(size_t)r] = (*perm)[g % n_rows];
      }
    } else {
      for (int64_t r = 0; r < rows; ++r)
        src_rows[(size_t)r] = (start + r) % n_rows;
    }
    for (size_t a = 0; a < data.size(); ++a) {
      uint8_t* dst = slot.buffers[a].data();
      size_t rb = row_bytes[a];
      for (int64_t r = 0; r < rows; ++r) {
        std::memcpy(dst + (size_t)r * rb,
                    data[a] + (size_t)src_rows[(size_t)r] * rb, rb);
      }
    }
    slot.rows = rows;
  }

  void worker() {
    for (;;) {
      int64_t b = next_batch.fetch_add(1);
      if (stop.load() || (total_batches >= 0 && b >= total_batches)) return;
      int64_t depth = (int64_t)slots.size();
      Slot& slot = slots[(size_t)(b % depth)];
      {
        // a slot is free for batch b only once batch b-depth (its previous
        // occupant) has been drained — "not ready" alone can't distinguish
        // being-filled from consumed
        std::unique_lock<std::mutex> lk(slot.mu);
        slot.cv.wait(lk, [&] {
          return stop.load() || slot.consumed_id == b - depth;
        });
        if (stop.load()) return;
        slot.batch_id = b;
      }
      fill(slot, b);
      {
        std::lock_guard<std::mutex> lk(slot.mu);
        slot.ready = true;
      }
      slot.cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* tfde_loader_create(
    int n_arrays, const void** data, const int64_t* row_bytes, int64_t n_rows,
    int64_t batch, int drop_remainder, int shuffle, uint64_t seed,
    int64_t repeat /* -1 = infinite */, int num_threads, int depth) {
  if (n_arrays <= 0 || n_rows <= 0 || batch <= 0) return nullptr;
  auto* L = new Loader();
  L->data.assign((const uint8_t**)data, (const uint8_t**)data + n_arrays);
  L->row_bytes.assign(row_bytes, row_bytes + n_arrays);
  L->n_rows = n_rows;
  L->batch = batch;
  L->drop_remainder = drop_remainder != 0;
  L->shuffle = shuffle != 0;
  L->seed = seed;
  L->repeat = repeat;
  if (repeat < 0) {
    L->total_batches = -1;
  } else {
    int64_t total_rows = repeat * n_rows;
    L->total_batches =
        L->drop_remainder ? total_rows / batch : (total_rows + batch - 1) / batch;
  }
  if (depth < 2) depth = 2;
  L->slots = std::vector<Slot>((size_t)depth);
  for (size_t i = 0; i < L->slots.size(); ++i) {
    Slot& s = L->slots[i];
    s.buffers.resize((size_t)n_arrays);
    for (int a = 0; a < n_arrays; ++a)
      s.buffers[(size_t)a].resize((size_t)batch * (size_t)row_bytes[a]);
    s.batch_id = -1;
    s.consumed_id = (int64_t)i - (int64_t)depth;  // slot i starts free for batch i
  }
  if (num_threads < 1) num_threads = 1;
  int max_threads = depth > 1 ? depth - 1 : 1;  // keep >=1 slot drainable
  if (num_threads > max_threads) num_threads = max_threads;
  for (int t = 0; t < num_threads; ++t)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

// Blocks for the next batch. Returns rows in the batch (0 = end of data or
// loader stopped). Buffer pointers for each array are written to out_ptrs;
// they stay valid until the matching tfde_loader_release call.
int64_t tfde_loader_next(void* handle, void** out_ptrs) {
  auto* L = (Loader*)handle;
  // Count the consumer in so a concurrent destroy waits for it to leave
  // before freeing the loader (destroy racing a blocked next() used to
  // hang the worker join — and, fixed, would otherwise free slot.mu while
  // the waiter still held it).
  L->active_next.fetch_add(1);
  struct Dec {
    std::atomic<int>* c;
    ~Dec() { c->fetch_sub(1); }
  } dec{&L->active_next};
  if (L->stop.load()) return 0;
  int64_t b = L->consumed;
  if (L->total_batches >= 0 && b >= L->total_batches) return 0;
  Slot& slot = L->slots[(size_t)b % L->slots.size()];
  std::unique_lock<std::mutex> lk(slot.mu);
  slot.cv.wait(lk, [&] {
    return L->stop.load() || (slot.ready && slot.batch_id == b);
  });
  if (L->stop.load()) return 0;
  for (size_t a = 0; a < L->data.size(); ++a)
    out_ptrs[a] = slot.buffers[a].data();
  return slot.rows;
}

// Releases the slot of the most recently next()ed batch for refill.
void tfde_loader_release(void* handle) {
  auto* L = (Loader*)handle;
  int64_t b = L->consumed;
  Slot& slot = L->slots[(size_t)b % L->slots.size()];
  {
    std::lock_guard<std::mutex> lk(slot.mu);
    slot.ready = false;
    slot.consumed_id = b;
  }
  L->consumed = b + 1;
  slot.cv.notify_all();
}

// Stop workers and wake any blocked consumer WITHOUT freeing — phase one of
// a safe cross-thread shutdown. The Python binding calls stop, waits for its
// consumers to drain out of next() (they return 0), then calls destroy; a
// consumer that captured the handle just before close() swapped it away can
// still safely enter next() between stop and destroy.
void tfde_loader_stop(void* handle) {
  auto* L = (Loader*)handle;
  L->stop.store(true);
  for (auto& s : L->slots) s.cv.notify_all();
}

// crc32c (Castagnoli) — slice-by-8 table walk. The TFRecord framing CRC is
// the decode-path bottleneck in Python (measured 13k rec/s table loop vs
// 1M rec/s for everything else, tests/test_streaming.py); at C speed the
// check is effectively free, so streaming readers keep corruption
// detection on.
static uint32_t crc_tables[8][256];
static bool crc_init_done = []() {
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    crc_tables[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int t = 1; t < 8; ++t)
      crc_tables[t][i] =
          crc_tables[0][crc_tables[t - 1][i] & 0xFF] ^ (crc_tables[t - 1][i] >> 8);
  return true;
}();

uint32_t tfde_crc32c(const uint8_t* data, int64_t n) {
  uint32_t c = 0xFFFFFFFFu;
  const uint8_t* p = data;
  while (n >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    w ^= c;  // little-endian hosts only (this toolchain's targets)
    c = crc_tables[7][w & 0xFF] ^ crc_tables[6][(w >> 8) & 0xFF] ^
        crc_tables[5][(w >> 16) & 0xFF] ^ crc_tables[4][(w >> 24) & 0xFF] ^
        crc_tables[3][(w >> 32) & 0xFF] ^ crc_tables[2][(w >> 40) & 0xFF] ^
        crc_tables[1][(w >> 48) & 0xFF] ^ crc_tables[0][(w >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n-- > 0) c = crc_tables[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

void tfde_loader_destroy(void* handle) {
  auto* L = (Loader*)handle;
  L->stop.store(true);
  for (auto& s : L->slots) s.cv.notify_all();
  for (auto& t : L->workers) t.join();
  // Wait out any consumer still inside next() (it wakes on stop and returns
  // 0); deleting while it holds slot.mu would be use-after-free.
  while (L->active_next.load() != 0) {
    for (auto& s : L->slots) s.cv.notify_all();
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  delete L;
}

}  // extern "C"
