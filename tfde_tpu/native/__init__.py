"""ctypes bindings for the native host data loader (loader.cc).

Build model: `g++ -O3 -shared -fPIC` on first use, cached next to the source
(keyed by source hash, so edits rebuild). No pybind11 in this environment —
the C ABI + ctypes keeps the binding dependency-free. `available()` gates
call sites; the pure-Python pipeline (data/pipeline.py) is the documented
fallback so the framework degrades gracefully where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
import time
from pathlib import Path
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from tfde_tpu import knobs

log = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "loader.cc"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    cache_dir = Path(
        knobs.env_str("TFDE_NATIVE_CACHE") or Path.home() / ".cache" / "tfde_tpu"
    )
    cache_dir.mkdir(parents=True, exist_ok=True)
    so = cache_dir / f"loader_{tag}.so"
    if not so.exists():
        tmp = so.with_suffix(f".so.{os.getpid()}.tmp")  # concurrent builders
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            str(_SRC), "-o", str(tmp),
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
    return ctypes.CDLL(str(so))


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            lib = _build()
            lib.tfde_loader_create.restype = ctypes.c_void_p
            lib.tfde_loader_create.argtypes = [
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
                ctypes.c_uint64, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ]
            lib.tfde_loader_next.restype = ctypes.c_int64
            lib.tfde_loader_next.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)
            ]
            lib.tfde_loader_release.argtypes = [ctypes.c_void_p]
            lib.tfde_loader_stop.argtypes = [ctypes.c_void_p]
            lib.tfde_loader_destroy.argtypes = [ctypes.c_void_p]
            lib.tfde_crc32c.restype = ctypes.c_uint32
            lib.tfde_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            _lib = lib
        except Exception as e:  # no toolchain / build error -> python fallback
            log.warning("native loader unavailable (%s); using python pipeline", e)
            _build_failed = True
    return _lib


def available() -> bool:
    return _get_lib() is not None


def crc32c(data: bytes) -> Optional[int]:
    """Native crc32c (Castagnoli), or None when the library is unavailable
    (caller falls back to the Python table walk). ~100x the Python loop —
    the difference between a CRC-checked streaming TFRecord reader being
    IO-bound and being checksum-bound (tests/test_streaming.py)."""
    lib = _get_lib()
    if lib is None:
        return None
    return int(lib.tfde_crc32c(data, len(data)))


class NativeBatchLoader:
    """Threaded shuffle+gather+prefetch over in-memory arrays.

    The hot-loop host path: per-epoch permutation, memcpy row gather, and a
    `depth`-deep prefetch ring all run in GIL-free C++ threads. Semantics
    match data/pipeline.py's `shuffle(n).repeat(r).batch(b)` chain (tf.data
    repeat().batch(): batches cross epoch boundaries; final short batch
    unless drop_remainder).

    When it pays: at MNIST-sized rows the numpy fancy-index fast path is
    already memory-bound-optimal (measured parity, ~0.8-1.0x); at
    scale-config batch sizes the multi-worker gather pulls ahead decisively
    (measured 3.7x at 13 MB/batch — 5.8 vs 1.6 GB/s on this host). Use it
    for the ResNet/ViT input paths; MNIST examples keep the python
    pipeline.

    Yields tuples of numpy arrays. Yielded views alias the slot buffer and
    are only valid until the next iteration — consume (e.g. device_put) or
    copy before advancing; pass `copy=True` to get owned arrays.
    """

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        repeat: Optional[int] = None,  # None = infinite
        drop_remainder: bool = False,
        num_threads: int = 2,
        depth: int = 4,
        copy: bool = False,
    ):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(
                "native loader unavailable; use data.pipeline.Dataset instead"
            )
        self._lib = lib
        self._arrays = [np.ascontiguousarray(a) for a in arrays]
        n = self._arrays[0].shape[0]
        if any(a.shape[0] != n for a in self._arrays):
            raise ValueError("all arrays must share the leading dimension")
        self._batch = int(batch_size)
        self._copy = copy
        self._row_shapes = [a.shape[1:] for a in self._arrays]
        self._dtypes = [a.dtype for a in self._arrays]
        row_bytes = [int(a.strides[0]) if a.ndim > 1 else a.itemsize
                     for a in self._arrays]

        n_arr = len(self._arrays)
        ptrs = (ctypes.c_void_p * n_arr)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self._arrays]
        )
        rb = (ctypes.c_int64 * n_arr)(*row_bytes)
        self._handle = lib.tfde_loader_create(
            n_arr, ptrs, rb, n, self._batch,
            int(drop_remainder), int(shuffle), seed,
            -1 if repeat is None else int(repeat),
            num_threads, depth,
        )
        if not self._handle:
            raise RuntimeError("tfde_loader_create failed")
        self._out = (ctypes.c_void_p * n_arr)()
        self._pending_release = False
        self._close_lock = threading.Lock()
        self._in_next = 0  # consumers currently inside the native call

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        return self

    def __next__(self) -> Tuple[np.ndarray, ...]:
        # capture the handle and count ourselves in under the lock, so a
        # concurrent close() either (a) sees us and defers the free until we
        # drain, or (b) swapped the handle first and we stop here — the
        # handle can never be freed between our check and the native call
        with self._close_lock:
            handle = self._handle
            if handle is None:
                raise StopIteration
            self._in_next += 1
        try:
            if self._pending_release:
                self._lib.tfde_loader_release(handle)
                self._pending_release = False
            rows = self._lib.tfde_loader_next(handle, self._out)
        finally:
            with self._close_lock:
                self._in_next -= 1
        if rows == 0:
            self.close()
            raise StopIteration
        out = []
        for i, (shape, dtype) in enumerate(zip(self._row_shapes, self._dtypes)):
            nbytes = int(rows) * int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            buf = (ctypes.c_char * nbytes).from_address(self._out[i])
            arr = np.frombuffer(buf, dtype=dtype).reshape((int(rows),) + shape)
            out.append(arr.copy() if self._copy else arr)
        self._pending_release = True
        return tuple(out)

    def close(self) -> None:
        """Stop workers and free the loader. Safe to call from a second
        thread while a consumer is anywhere in ``__next__``: stop() wakes a
        blocked waiter (it raises StopIteration), we wait for in-flight
        consumers to drain, and only then free — two phases, so a consumer
        that captured the handle just before the swap still lands on live
        memory."""
        with self._close_lock:
            handle, self._handle = self._handle, None
        if handle is None:
            return
        self._lib.tfde_loader_stop(handle)
        while True:
            with self._close_lock:
                if self._in_next == 0:
                    break
            time.sleep(0.001)
        self._lib.tfde_loader_destroy(handle)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
