"""Sharded train-state checkpointing with auto-resume.

The reference's checkpoint contract (SURVEY.md §5): Estimator saves every
`save_checkpoints_steps=500` into `model_dir` (mnist_keras:245-248), restarted
processes transparently resume from the latest checkpoint, and `--working-dir`
may be a remote (GCS) path (mnist_keras:41-44). TPU-native equivalent: Orbax
async checkpointing of the {step, params, batch_stats, opt_state} pytree —
each host writes only its own shards of sharded arrays, restore respects the
target shardings, and writes go through Orbax's atomic-rename protocol (the
SaveV2/RestoreV2 + MonitoredTrainingSession analog).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any, Optional

import jax
import orbax.checkpoint as ocp

from tfde_tpu.observability import metrics
from tfde_tpu.observability.spans import span
from tfde_tpu.resilience.policy import RetryPolicy, policy_from_env, retry_call

if TYPE_CHECKING:  # avoid the training<->checkpoint import cycle at runtime
    from tfde_tpu.training.train_state import TrainState

log = logging.getLogger(__name__)


class CheckpointManager:
    """Thin Orbax wrapper bound to a model_dir.

    Saves the pytree-node part of a TrainState (apply_fn/tx are static code,
    not state). `restore_latest` returns a state with the *caller's* shardings
    — pass the live/abstract state so restored arrays land where training
    expects them.

    Save/restore are fallible remote I/O (gs:// blips are routine at pod
    scale), so both run under a retry policy — the operator's
    ``TFDE_RETRY_*`` knobs by default, or an explicit `retry_policy`.
    Retries only transient classes (OSError/timeouts); a structure-mismatch
    ValueError still fails fast on the first attempt.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: Optional[int] = 5,
        async_save: bool = True,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self._dir = directory
        self._retry = retry_policy or policy_from_env()
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)

    # -- save ---------------------------------------------------------------
    def save(self, state: "TrainState", force: bool = False) -> bool:
        step = int(jax.device_get(state.step))
        if step in (self._mngr.all_steps() or ()):  # already on disk
            return False
        with span("checkpoint/save"):
            saved = retry_call(
                self._mngr.save,
                step,
                args=ocp.args.StandardSave(self._tree(state)),
                force=force,
                policy=self._retry,
                what=f"checkpoint save(step={step})",
                counter="resilience/checkpoint_retries",
            )
        if saved:
            metrics.counter("checkpoint/saves").incr()
            metrics.gauge("checkpoint/latest_saved_step").set(step)
            log.info("checkpoint saved at step %d -> %s", step, self._dir)
            from tfde_tpu.observability import flightrec

            flightrec.record("ckpt_save", step=step, forced=bool(force))
        return saved

    def wait(self) -> None:
        """Block until pending async saves commit (call before process exit)."""
        with span("checkpoint/wait"):
            self._mngr.wait_until_finished()

    # -- restore ------------------------------------------------------------
    @property
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def reload(self) -> None:
        """Re-read the checkpoint directory. Orbax caches the step listing
        at construction; an evaluator job following a live trainer's
        model_dir must reload to see checkpoints written since."""
        self._mngr.reload()

    def restore_latest(self, state: "TrainState") -> Optional["TrainState"]:
        """Resume-by-default: restore the newest checkpoint into the given
        state's shardings, or None if the directory has no checkpoint."""
        step = self._mngr.latest_step()
        if step is None:
            return None
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding")
            else x,
            self._tree(state),
        )
        if self._packed_geometry_differs(step, state):
            # ZeRO checkpoint from a DIFFERENT world size: same container
            # skeleton, different [N, C] chunk shapes. The direct path must
            # not even be attempted — orbax does not reliably reject the
            # shape change (with sharded targets it can silently reshard
            # the wrong bytes into the new chunks), so route straight to
            # the cross-format bridge's re-chunk branch.
            bridged = self._restore_cross_format(step, state, abstract)
            if bridged is not None:
                log.info(
                    "restored checkpoint step %d from %s "
                    "(cross-format opt state)",
                    int(jax.device_get(bridged.step)), self._dir,
                )
                from tfde_tpu.observability import flightrec

                flightrec.record(
                    "ckpt_restore",
                    step=int(jax.device_get(bridged.step)),
                    cross_format=True,
                )
                return bridged
            raise ValueError(
                f"checkpoint step {step} in {self._dir} holds ZeRO-packed "
                f"optimizer state with a different chunk geometry than the "
                f"current state (written at a different world size or with "
                f"different comms blocking), and the cross-world re-chunk "
                f"could not bridge it. Resume at the writer's world size, "
                f"or clear the checkpoint directory to restart"
            )
        try:
            # NOTE goodput accounting: restores run inside the train loop's
            # init span, so "checkpoint/restore" is observability-only and
            # the ledger's checkpoint category counts save+wait alone
            import time as _time

            t_restore = _time.perf_counter()
            with span("checkpoint/restore"):
                restored = retry_call(
                    self._mngr.restore,
                    step,
                    args=ocp.args.StandardRestore(abstract),
                    policy=self._retry,
                    what=f"checkpoint restore(step={step})",
                    counter="resilience/checkpoint_retries",
                )
            self._note_boot_restore(
                restored, _time.perf_counter() - t_restore)
        except ValueError as e:
            # Reword ONLY genuine structure mismatches: compare the saved
            # checkpoint's tree structure (orbax metadata) against the
            # requested abstract tree, instead of sniffing the error text —
            # an unrelated ValueError that happens to mention "structure"
            # must surface unrelabeled.
            if (self._saved_structure_differs(step, abstract)
                    or self._packed_geometry_differs(step, state)):
                bridged = self._restore_cross_format(step, state, abstract)
                if bridged is not None:
                    log.info(
                        "restored checkpoint step %d from %s "
                        "(cross-format opt state)",
                        int(jax.device_get(bridged.step)), self._dir,
                    )
                    from tfde_tpu.observability import flightrec

                    flightrec.record(
                        "ckpt_restore",
                        step=int(jax.device_get(bridged.step)),
                        cross_format=True,
                    )
                    return bridged
                raise ValueError(
                    f"checkpoint step {step} in {self._dir} does not match "
                    f"the current train state's structure — most commonly "
                    f"the optimizer configuration changed since it was "
                    f"written (e.g. a decay mask wraps the opt state). "
                    f"Resume with the original optimizer, or clear the "
                    f"checkpoint directory to restart"
                ) from e
            raise
        log.info("restored checkpoint step %d from %s", step, self._dir)
        from tfde_tpu.observability import flightrec

        flightrec.record("ckpt_restore", step=step)
        return state.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
        )

    @staticmethod
    def _note_boot_restore(restored, seconds: float) -> None:
        """Feed the boot ledger's restore accounting: per-top-level-leaf
        bytes of the restored tree plus the restore call's wall become
        ``boot/restore_bandwidth_bps`` — the streamed-restore baseline a
        joining replica's cold start is measured against. Best-effort:
        a ledger failure must never fail a restore."""
        try:
            from tfde_tpu.observability import boot as boot_lib

            leaves = {}
            for name, sub in restored.items():
                nb = sum(int(getattr(x, "nbytes", 0))
                         for x in jax.tree_util.tree_leaves(sub))
                if nb:
                    leaves[str(name)] = nb
            if leaves:
                boot_lib.note_restore(leaves, seconds)
        except Exception:
            log.debug("boot restore accounting failed", exc_info=True)

    @staticmethod
    def _find_packed(node):
        """First ZeRO packed-slot dict (exactly {packed_big, packed_small})
        in an orbax metadata tree, or None. Marks a checkpoint written with
        opt_sharding='shard' (parallel/zero.py)."""
        if isinstance(node, dict):
            if set(node.keys()) == {"packed_big", "packed_small"}:
                return node
            children = node.values()
        elif isinstance(node, (list, tuple)):
            children = node
        else:
            return None
        for child in children:
            found = CheckpointManager._find_packed(child)
            if found is not None:
                return found
        return None

    def _restore_cross_format(self, step, state, abstract):
        """Bridge optimizer-state formats on restore: a checkpoint written
        with opt_sharding='replicated' resumed into a ZeRO-sharded state
        (pack after a replicated restore), one written with 'shard' resumed
        into a replicated state (restore the packed slots, then unpack), or
        one written with 'shard' at a DIFFERENT world size resumed into a
        ZeRO-sharded state (restore under the writer's M-way layout, then
        re-chunk to the live N-way layout — the elastic shrink/grow path,
        both M>N and M<N). All directions are bit-exact — pack/unpack/
        relayout are pure reshapes of the same numbers. Conservative: any
        failure returns None and the direct path's structure-mismatch
        guidance surfaces instead."""
        try:
            from jax.sharding import NamedSharding, PartitionSpec
            from tfde_tpu.parallel import comms as comms_lib
            from tfde_tpu.parallel import zero as zero_lib

            meta = self._item_meta(step)
            saved_packed = self._find_packed(meta["opt_state"])
            layout = getattr(state, "opt_layout", None)
            leaves = jax.tree_util.tree_leaves(state.params)
            if not leaves:
                return None
            psh = leaves[0].sharding
            rep = (NamedSharding(psh.mesh, PartitionSpec())
                   if hasattr(psh, "mesh") else psh)

            def abstract_rep(tree):
                return jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                   sharding=rep),
                    tree,
                )

            if layout is not None and saved_packed is None:
                # saved replicated -> live sharded: restore the
                # params-congruent slots fully replicated, pack, reshard
                ab_opt = abstract_rep(jax.eval_shape(state.tx.init,
                                                     state.params))
                restored = self._restore_opt_variant(step, abstract, ab_opt)
                opt = zero_lib.pack_opt_state(restored["opt_state"], layout)
            elif layout is None and saved_packed is not None:
                # saved sharded -> live replicated: rebuild the writer's
                # layout from the packed shapes, restore, unpack
                big_shape = tuple(saved_packed["packed_big"].shape)
                cand = zero_lib.build_layout(
                    state.params, comms_lib.CommsConfig(), int(big_shape[0]))
                if (big_shape != (cand.nshards, cand.chunk_big)
                        or tuple(saved_packed["packed_small"].shape)
                        != (cand.nshards, cand.chunk_small)):
                    return None  # non-default comms block/threshold knobs
                ab_opt = abstract_rep(jax.eval_shape(
                    lambda p: state.tx.init(zero_lib.pack_params(p, cand)),
                    state.params,
                ))
                restored = self._restore_opt_variant(step, abstract, ab_opt)
                opt = zero_lib.unpack_opt_state(restored["opt_state"], cand)
            elif layout is not None and saved_packed is not None:
                # saved sharded M-way -> live sharded N-way: reconstruct
                # the writer's layout from the live one (same params, same
                # block; only nshards differs), restore the packed slots
                # replicated under it, then re-chunk to the live layout
                saved_n = int(saved_packed[zero_lib.BIG].shape[0])
                cand = zero_lib.with_nshards(layout, saved_n)
                if (tuple(saved_packed[zero_lib.BIG].shape)
                        != (cand.nshards, cand.chunk_big)
                        or tuple(saved_packed[zero_lib.SMALL].shape)
                        != (cand.nshards, cand.chunk_small)):
                    return None  # different params or comms block knobs
                ab_opt = abstract_rep(jax.eval_shape(
                    lambda p: state.tx.init(zero_lib.pack_params(p, cand)),
                    state.params,
                ))
                restored = self._restore_opt_variant(step, abstract, ab_opt)
                opt = zero_lib.relayout_opt_state(
                    restored["opt_state"], cand, layout)
            else:
                return None
            opt = jax.device_put(
                opt,
                jax.tree_util.tree_map(lambda x: x.sharding, state.opt_state),
            )
            return state.replace(
                step=restored["step"],
                params=restored["params"],
                batch_stats=restored["batch_stats"],
                opt_state=opt,
            )
        except Exception:
            log.debug("cross-format restore attempt failed", exc_info=True)
            return None

    def _restore_opt_variant(self, step, abstract, ab_opt):
        """Restore with the direct path's abstract tree, opt_state swapped
        for the other format's abstract."""
        alt = dict(abstract)
        alt["opt_state"] = ab_opt
        return retry_call(
            self._mngr.restore,
            step,
            args=ocp.args.StandardRestore(alt),
            policy=self._retry,
            what=f"checkpoint restore(step={step}, cross-format)",
            counter="resilience/checkpoint_retries",
        )

    @staticmethod
    def _normalize_structure(tree):
        """Container skeleton of a pytree in orbax-metadata-comparable
        form: namedtuples (optax states) -> {field: ...} dicts (metadata
        loses the namedtuple class), plain tuples/lists -> lists, empty
        containers -> None (metadata collapses e.g. optax.EmptyState() to
        a leaf), every leaf -> None. Verified empirically: a matching
        adamw state normalizes equal to its saved metadata; an
        sgd(momentum) state against an adamw checkpoint does not."""
        n = CheckpointManager._normalize_structure
        if isinstance(tree, tuple) and hasattr(tree, "_fields"):
            return {f: n(v) for f, v in zip(tree._fields, tree)} or None
        if isinstance(tree, dict):
            return {k: n(v) for k, v in tree.items()} or None
        if isinstance(tree, (list, tuple)):
            return [n(v) for v in tree] or None
        return None

    def _packed_geometry_differs(self, step: int, state) -> bool:
        """True when both the checkpoint and the live state hold ZeRO-packed
        optimizer slots but with different chunk geometry — a checkpoint
        written at a different world size. The container skeletons are
        IDENTICAL in that case (same {packed_big, packed_small} dicts, only
        the [N, C] shapes moved), so `_saved_structure_differs` cannot see
        it; this is the trigger that routes the elastic M-way -> N-way
        restore through the cross-format bridge. Conservative like its
        sibling: any failure reading metadata returns False."""
        try:
            from tfde_tpu.parallel import zero as zero_lib

            layout = getattr(state, "opt_layout", None)
            if layout is None:
                return False
            meta = self._item_meta(step)
            saved = self._find_packed(meta["opt_state"])
            if saved is None:
                return False
            return (tuple(saved[zero_lib.BIG].shape)
                    != (layout.nshards, layout.chunk_big)
                    or tuple(saved[zero_lib.SMALL].shape)
                    != (layout.nshards, layout.chunk_small))
        except Exception:
            return False

    def _item_meta(self, step: int):
        """Metadata tree of the saved checkpoint at `step`. The manager's
        own `item_metadata` returns None until a save/restore registered
        the item handler — a fresh manager that has done neither (the
        restart/elastic-restore case) falls back to a standalone
        StandardCheckpointHandler read of the step's item directory."""
        meta = self._mngr.item_metadata(step)
        if meta is None:
            import os

            ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
            try:
                meta = ckptr.metadata(os.path.join(self._dir, str(step),
                                                   "default"))
            finally:
                ckptr.close()
        # newer orbax wraps the tree in a metadata object; older returns
        # the (dict) tree itself
        return getattr(meta, "tree", meta)

    def _saved_structure_differs(self, step: int, abstract) -> bool:
        """True when the on-disk checkpoint's pytree structure differs from
        the tree we asked to restore into — the condition the optimizer-
        changed guidance in restore_latest is about. Conservative: any
        failure reading metadata returns False (the original error then
        propagates untouched)."""
        try:
            meta = self._item_meta(step)
            return (self._normalize_structure(meta)
                    != self._normalize_structure(abstract))
        except Exception:
            return False

    def close(self) -> None:
        self._mngr.close()

    @staticmethod
    def _tree(state: "TrainState") -> dict:
        return {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
