"""Checkpointing: sharded async save + transparent resume (SURVEY.md §5)."""

from tfde_tpu.checkpoint.manager import CheckpointManager  # noqa: F401
