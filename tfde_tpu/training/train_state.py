"""Train state: the checkpointable unit {step, params, batch_stats, opt_state}.

The analog of the reference's checkpoint contents (global step + variables +
optimizer slots saved by SaveV2 every 500 steps, mnist_keras:245-248), as one
pytree so Orbax can shard-save it and `jit` can donate it whole.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import optax


class TrainState(flax.struct.PyTreeNode):
    step: Any
    params: Any
    batch_stats: Any
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads, new_batch_stats=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
            opt_state=new_opt_state,
        )
