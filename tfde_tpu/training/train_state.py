"""Train state: the checkpointable unit {step, params, batch_stats, opt_state}.

The analog of the reference's checkpoint contents (global step + variables +
optimizer slots saved by SaveV2 every 500 steps, mnist_keras:245-248), as one
pytree so Orbax can shard-save it and `jit` can donate it whole.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import optax


class TrainState(flax.struct.PyTreeNode):
    step: Any
    params: Any
    batch_stats: Any
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    # Error-feedback residual for the quantized gradient transport
    # (parallel/comms.py): params-congruent fp32 tree holding what the int8
    # quantizer dropped last step, re-injected into the next exchange. None
    # under grad_transport='fp32' — None is an empty pytree, so the default
    # keeps the state structure (and every existing checkpoint/jaxpr)
    # byte-identical. Per-device contents (each replica carries ITS OWN
    # compression error); only the exchange ever reads it. Deliberately
    # NOT checkpointed (checkpoint/manager.py saves {step, params,
    # batch_stats, opt_state}): a resumed run restarts the residual from
    # zeros — a few warm-up steps of extra quantization error, and
    # fp32<->int8 checkpoint resume stays compatible in both directions.
    comm_residual: Any = None

    def apply_gradients(self, grads, new_batch_stats=None,
                        new_comm_residual=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
            opt_state=new_opt_state,
            comm_residual=(
                new_comm_residual if new_comm_residual is not None
                else self.comm_residual
            ),
        )
