"""Train state: the checkpointable unit {step, params, batch_stats, opt_state}.

The analog of the reference's checkpoint contents (global step + variables +
optimizer slots saved by SaveV2 every 500 steps, mnist_keras:245-248), as one
pytree so Orbax can shard-save it and `jit` can donate it whole.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import optax


class TrainState(flax.struct.PyTreeNode):
    step: Any
    params: Any
    batch_stats: Any
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    # Error-feedback residual for the quantized gradient transport
    # (parallel/comms.py): params-congruent fp32 tree holding what the int8
    # quantizer dropped last step, re-injected into the next exchange. None
    # under grad_transport='fp32' — None is an empty pytree, so the default
    # keeps the state structure (and every existing checkpoint/jaxpr)
    # byte-identical. Per-device contents (each replica carries ITS OWN
    # compression error); only the exchange ever reads it. Deliberately
    # NOT checkpointed (checkpoint/manager.py saves {step, params,
    # batch_stats, opt_state}): a resumed run restarts the residual from
    # zeros — a few warm-up steps of extra quantization error, and
    # fp32<->int8 checkpoint resume stays compatible in both directions.
    comm_residual: Any = None
    # ZeRO weight-update sharding (parallel/zero.py): the static chunk
    # layout when the optimizer state is packed/sharded over the data axis
    # ({packed_big: [N, Cb], packed_small: [N, Cs]} slots instead of
    # params-congruent ones), or None for the replicated default. Static
    # (non-pytree) so the step builder can branch on it at trace time; a
    # Layout is hashable, so treedefs still compare/jit-cache correctly.
    opt_layout: Any = flax.struct.field(pytree_node=False, default=None)

    @property
    def opt_sharded(self) -> bool:
        return self.opt_layout is not None

    def apply_chunk_gradients(self, grad_chunks, param_chunks):
        """The ZeRO owner-chunk update: run the optimizer on this replica's
        1/N packed slice only. `grad_chunks`/`param_chunks` are local
        {packed_big: [1, Cb], packed_small: [1, Cs]} trees and
        `self.opt_state` the matching local slice (inside the step's
        shard_map body). Returns (new_param_chunks, new_opt_state). For
        elementwise transforms this is bit-identical to the replicated
        per-leaf update — see parallel/zero.py's correctness contract."""
        updates, new_opt_state = self.tx.update(
            grad_chunks, self.opt_state, param_chunks
        )
        return optax.apply_updates(param_chunks, updates), new_opt_state

    def apply_gradients(self, grads, new_batch_stats=None,
                        new_comm_residual=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
            opt_state=new_opt_state,
            comm_residual=(
                new_comm_residual if new_comm_residual is not None
                else self.comm_residual
            ),
        )
