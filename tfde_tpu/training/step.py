"""Compiled train/eval steps — the hot loop (SURVEY.md §3, "HOT LOOP").

One traced computation serves every strategy: the batch arrives sharded over
the mesh's data axes, params/opt-state carry the strategy's shardings, and the
XLA SPMD partitioner inserts the gradient `psum` (replacing the reference's
CollectiveAllReduce, distributed_with_keras.py:16) or reduce-scatter/all-gather
pairs (ZeRO/FSDP, the ParameterServerStrategy capability). No hand-written
collectives, per the design rule in SURVEY.md §2b.

Loss convention: mean over the *global* batch == sum x 1/global_batch
(tf2_mnist_distributed.py:81-83); see ops/losses.py.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tfde_tpu.ops import losses, metrics as metrics_lib
from tfde_tpu.parallel import axes as axes_lib
from tfde_tpu.parallel import comms as comms_lib
from tfde_tpu.parallel import zero as zero_lib
from tfde_tpu.parallel.strategies import Strategy
from tfde_tpu.training.train_state import TrainState
from tfde_tpu.utils import compat

log = logging.getLogger(__name__)


def sown_losses_by_name(mutated_losses) -> dict:
    """Group everything sown into the 'losses' collection by its final sown
    name (e.g. 'moe_aux', 'moe_z'), summed across layers. The ONE
    definition of "every sown loss joins the objective" — used by the
    default classification path (`_forward`) and the custom-LM path
    (models/gpt.py `next_token_loss`); sow() into an immutable collection
    is a silent no-op, so any apply that skips this drops the MoE
    load-balance term."""
    by_name: dict = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(mutated_losses):
        keys = [getattr(p, "key", None) for p in path]
        name = next((k for k in reversed(keys) if isinstance(k, str)), "aux")
        by_name[name] = by_name.get(name, 0.0) + jnp.sum(leaf)
    return by_name


def _forward(state: TrainState, params, images, train: bool, dropout_rng=None):
    """Returns (logits, new_batch_stats, aux_loss). aux_loss collects every
    value the model sows into the 'losses' collection (e.g. the MoE
    load-balance loss, models/moe.py) so routed models train correctly under
    the default classification step too."""
    variables = {"params": params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    kwargs = {}
    if dropout_rng is not None:
        kwargs["rngs"] = {"dropout": dropout_rng}
    if train:
        logits, mutated = state.apply_fn(
            variables, images, train=True,
            mutable=["batch_stats", "losses"], **kwargs
        )
        aux = sum(
            sown_losses_by_name(mutated.get("losses", {})).values()
        )
        return logits, mutated.get("batch_stats", state.batch_stats), aux
    logits = state.apply_fn(variables, images, train=train, **kwargs)
    return logits, state.batch_stats, jnp.zeros((), jnp.float32)


def _classification_loss(state: TrainState, params, batch, rng):
    """The default objective (tf2_mnist_distributed.py:81-83 semantics) in
    loss_fn form — the single definition behind both `train_step` and the
    grad-accum path, so they cannot drift."""
    images, labels = batch
    logits, new_stats, aux = _forward(
        state, params, images, train=True, dropout_rng=rng
    )
    loss = losses.sparse_categorical_crossentropy(logits, labels) + aux
    return loss, {
        "accuracy": metrics_lib.accuracy(logits, labels),
        "batch_stats": new_stats,
    }


def train_step(
    state: TrainState, batch: Tuple[jax.Array, jax.Array], rng: jax.Array
) -> Tuple[TrainState, dict]:
    """One SGD step. batch = (images, int labels); returns (state, metrics)."""
    step_rng = jax.random.fold_in(rng, state.step)

    def loss_fn(params):
        return _classification_loss(state, params, batch, step_rng)

    (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params
    )
    metrics = dict(metrics)
    new_stats = metrics.pop("batch_stats", state.batch_stats)
    new_state = state.apply_gradients(grads, new_batch_stats=new_stats)
    # global grad norm: the divergence/clipping telemetry every training
    # dashboard wants — computed from grads already in registers, one
    # scalar, summarized at the usual cadence by the lifecycle
    metrics["grad_norm"] = optax.global_norm(grads)
    return new_state, {"loss": loss, **metrics}


def eval_step(
    state: TrainState, batch: Tuple[jax.Array, jax.Array, jax.Array]
) -> dict:
    """Masked eval: batch = (images, labels, mask). The mask (1 for real
    examples, 0 for padding) lets ragged final eval batches — the reference
    batches the eval set without dropping the remainder (mnist_keras:147) —
    be padded up to the mesh's batch divisor while keeping exact metrics."""
    images, labels, mask = batch
    logits, _, _ = _forward(state, state.params, images, train=False)
    labels1d = labels.reshape(labels.shape[:1])
    per_ex = losses.softmax_cross_entropy_with_integer_labels(logits, labels)
    correct = (jnp.argmax(logits, axis=-1) == labels1d).astype(jnp.float32)
    # Sums, not means: the caller accumulates *on device* and fetches once at
    # the end of the pass — per-step host syncs would serialize eval on
    # high-latency links (each device_get is a full round trip).
    return {
        "loss_sum": jnp.sum(per_ex * mask),
        "correct_sum": jnp.sum(correct * mask),
        "weight": jnp.sum(mask),
    }


def _state_shardings(strategy: Strategy, state: TrainState):
    mesh = strategy.mesh

    def ns(spec_tree):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    if state.opt_layout is not None:
        # ZeRO-sharded optimizer state (parallel/zero.py): [N, C] chunk
        # leaves shard row-wise over the data axis — genuinely distributed
        # arrays, 1/N bytes per device, checkpointed shard-by-shard. On a
        # mesh whose data axis does not match the layout (e.g. an eval
        # strategy) the chunks replicate; only the train step needs them
        # distributed.
        daxis = comms_lib.data_axis(mesh)
        if daxis is not None and int(mesh.shape[daxis]) == state.opt_layout.nshards:
            opt_spec = zero_lib.opt_state_spec(
                state.opt_state, daxis, state.opt_layout.nshards
            )
        else:
            opt_spec = jax.tree_util.tree_map(lambda _: P(), state.opt_state)
    else:
        opt_spec = strategy.opt_state_spec(state.opt_state, state.params)
    return TrainState(
        step=NamedSharding(mesh, P()),
        params=ns(strategy.params_spec(state.params)),
        batch_stats=ns(
            jax.tree_util.tree_map(lambda _: P(), state.batch_stats)
        ),
        opt_state=ns(opt_spec),
        apply_fn=state.apply_fn,
        tx=state.tx,
        # error-feedback residual (parallel/comms.py): nominally replicated
        # — each device's copy differs, but only the exchange reads it, so
        # the claim is safe and XLA never moves the bytes
        comm_residual=ns(
            jax.tree_util.tree_map(lambda _: P(), state.comm_residual)
        ),
        opt_layout=state.opt_layout,  # static field: treedefs must match
    )


def init_state(
    model,
    tx,
    strategy: Strategy,
    sample_input: jax.Array,
    seed: int = 0,
) -> Tuple[TrainState, Any]:
    """Initialize a TrainState *directly sharded* per the strategy.

    Init runs under `jit` with `out_shardings` so large FSDP params
    materialize already-sharded (never a full replica per host). Returns
    (state, state_shardings).
    """
    mesh = strategy.mesh
    ccfg = comms_lib.effective(strategy.comms, mesh)

    def base_init(rng):
        # a tuple sample feeds multi-input models positionally (the T5
        # encoder-decoder takes (input_ids, decoder_input_ids)); a bare
        # array keeps the single-input contract every other family uses
        sample = jax.tree_util.tree_map(jnp.zeros_like, sample_input)
        args = sample if isinstance(sample, tuple) else (sample,)
        variables = model.init(rng, *args, train=False)
        return variables["params"], variables.get("batch_stats", {})

    # ZeRO weight-update sharding (parallel/zero.py): decide eligibility
    # from shapes alone, then init the optimizer on the PACKED params (tx
    # init depends on param values for e.g. param-EMA slots, so pack the
    # real values, not zeros) with the chunk arrays born sharded.
    layout = None
    if zero_lib.resolve(strategy.opt_sharding) == "shard":
        ab_params, _ = jax.eval_shape(base_init, jax.random.key(seed))
        zaxis = zero_lib.eligible_axis(strategy, ab_params)
        if zaxis is not None:
            if zero_lib.packable(jax.eval_shape(tx.init, ab_params)):
                layout = zero_lib.build_layout(
                    ab_params, ccfg, int(mesh.shape[zaxis])
                )
            else:
                log.warning(
                    "opt_sharding='shard' with a masked optimizer "
                    "(optax.masked / a decay mask) would re-evaluate the "
                    "mask on the packed tree — falling back to replicated"
                )

    def init_fn(rng):
        params, batch_stats = base_init(rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=(
                tx.init(zero_lib.pack_params(params, layout))
                if layout is not None else tx.init(params)
            ),
            apply_fn=model.apply,
            tx=tx,
            # int8 transport: allocate the error-feedback residual up
            # front so the step's carry structure is fixed. fp32 keeps
            # None — state structure (and checkpoints) byte-identical.
            comm_residual=(
                comms_lib.init_residual(params, ccfg)
                if ccfg.transport == "int8" else None
            ),
            opt_layout=layout,
        )

    abstract = jax.eval_shape(init_fn, jax.random.key(seed))
    shardings = _state_shardings(strategy, abstract)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.key(seed))
    return state, shardings


def _with_mesh(fn, mesh):
    """Trace `fn` under axes.use_axes(mesh) so the models' activation
    `constrain` annotations (parallel/axes.py) bind to the strategy's mesh.
    with_sharding_constraint is a trace-time op, so entering the context
    inside the traced body is exactly what pins it."""

    @functools.wraps(fn)
    def wrapped(*args):
        with axes_lib.use_axes(mesh):
            return fn(*args)

    return wrapped


def _sentried(step_fn, sentry_cfg):
    """Fuse the numerics sentry (observability/sentry.py) onto a step fn:
    the returned fn takes an extra device-side sentry carry and returns the
    updated carry. Pure jnp on metrics already in registers — the check
    compiles INTO the step (no second dispatch, no host callback); the host
    polls the carry's sticky flag only every poll_every steps."""
    from tfde_tpu.observability import sentry as sentry_lib

    def fused(state, batch, rng, sstate):
        new_state, m = step_fn(state, batch, rng)
        new_sstate = sentry_lib.update(
            sentry_cfg, sstate, new_state.step, m["loss"], m.get("grad_norm"),
            # int8 gradient transport (parallel/comms.py): the residual
            # norm feeds its EWMA; a quantizer overflow trips the sentry
            # instead of saturating silently
            residual_norm=m.get("comm_residual_norm"),
            comm_overflow=m.get("comm_overflow"),
        )
        return new_state, m, new_sstate

    return fused


def _resolve_comms(strategy: Strategy, state: TrainState, comms):
    """The one resolution point for the grad_transport knob: explicit arg >
    strategy knob ($TFDE_GRAD_TRANSPORT-aware), downgraded to fp32 on
    ineligible meshes (comms.effective) or when the state carries no
    error-feedback residual (e.g. built before the knob was set, or the
    LoRA path — the adapters are tiny; compressing them saves nothing)."""
    cfg = comms_lib.resolve(comms if comms is not None else strategy.comms)
    cfg = comms_lib.effective(cfg, strategy.mesh)
    if cfg.transport == "int8" and state.comm_residual is None:
        log.warning(
            "grad_transport='int8' but the TrainState has no comm_residual "
            "(built with fp32 transport?) — falling back to fp32. "
            "Re-init the state with the strategy's grad_transport set."
        )
        cfg = dataclasses.replace(cfg, transport="fp32")
    return cfg


def _resolve_opt_sharding(strategy: Strategy, state: TrainState,
                          opt_sharding=None) -> bool:
    """The one resolution point for the weight-update sharding knob
    (parallel/zero.py): the STATE's physical layout is authoritative — the
    optimizer state either is packed/sharded or it is not — and the knob
    (explicit arg > strategy > $TFDE_OPT_SHARDING) only gets to warn when
    it disagrees (state built before the knob was set, or an ineligible
    mesh already fell back at init)."""
    mode = zero_lib.resolve(
        opt_sharding if opt_sharding is not None else strategy.opt_sharding
    )
    if state.opt_layout is not None:
        if mode != "shard":
            log.warning(
                "opt_sharding='replicated' requested but the TrainState "
                "carries a sharded (packed) optimizer state — using the "
                "sharded update. Re-init the state to change layouts."
            )
        return True
    if mode == "shard":
        log.warning(
            "opt_sharding='shard' but the TrainState's optimizer state is "
            "replicated (built before the knob was set, or the mesh/"
            "optimizer was ineligible at init) — falling back to the "
            "replicated update. Re-init the state with the strategy's "
            "opt_sharding set."
        )
    return False


def _make_comms_step(strategy: Strategy, state: TrainState, loss_fn,
                     cfg: comms_lib.CommsConfig, grad_accum: int):
    """Build the explicit-exchange step fn: gradients computed per device
    on the LOCAL batch shard inside a `shard_map` over the data axis, then
    exchanged through the quantized all-reduce (parallel/comms.py) and/or
    updated through the ZeRO owner-chunk path (parallel/zero.py) instead
    of the partitioner's implicit fp32 psum + replicated update. Serves
    three of the four mode combinations (int8 x replicated — the original
    `_make_int8_step` — plus fp32/int8 x sharded); fp32 x replicated never
    reaches here, keeping that jaxpr byte-identical.

    The microbatch semantics match the fp32 path exactly: the device-major
    split there means global microbatch `a` is the concatenation of every
    device's a-th local sub-chunk — which is precisely the local
    [A, b_local/A] reshape here. Weighted accumulation decomposes too:
    sum_i sum_a w_ia * g_ia / sum w_ia over LOCAL masked means equals the
    global weighted update, because w*grad(masked mean) == grad(masked
    sum). Compression happens ONCE per update, after the accumulation —
    never per microbatch.

    Known (documented) deviations from the fp32 oracle: dropout keys fold
    in the shard index (per-shard masks instead of one global mask — same
    statistics, different bits), and BatchNorm batch statistics are the
    mean of per-shard statistics.

    Sharded-update collective budget (within PR 5's five-collective pin):
    fp32 x shard = sidecar psum + fp32 psum_scatter + param all_gather
    (3); int8 x shard = sidecar psum + scale pmax + int8 psum_scatter +
    param all_gather (4) — the gradient all-gather x2 of the replicated
    int8 path is REPLACED by one fp32 all-gather of updated params, which
    also carries each chunk's squared grad-norm so `grad_norm` costs no
    extra collective.
    """
    mesh = strategy.mesh
    axis = comms_lib.data_axis(mesh)
    nshards = int(mesh.shape[axis])
    apply_fn, tx = state.apply_fn, state.tx
    zlay = state.opt_layout
    mask_leaves = jax.tree_util.tree_leaves(
        comms_lib.compress_mask(state.params, cfg)
    )
    if zlay is not None:
        assert tuple(mask_leaves) == zlay.mask, (
            "opt_layout disagrees with the comms compress mask — state "
            "built under a different CommsConfig than the step's"
        )

    def micro_grads_local(pstate, mb, r):
        def wrapped(params):
            # no active mesh inside the manual region: the models'
            # activation `constrain` calls degrade to identity (they only
            # speak batch/model axes, all trivial on a per-device shard)
            with axes_lib.use_axes(None):
                return loss_fn(pstate, params, mb, r)

        (loss, metrics), grads = jax.value_and_grad(wrapped, has_aux=True)(
            pstate.params
        )
        metrics = dict(metrics)
        new_stats = metrics.pop("batch_stats", pstate.batch_stats)
        weight = metrics.pop("grad_weight", None)
        return grads, loss, metrics, new_stats, weight

    def as_weight(w):
        return (jnp.ones((), jnp.float32) if w is None
                else jnp.asarray(w, jnp.float32))

    def body(step_c, params, batch_stats, opt_local, residual, batch, key):
        shard = jax.lax.axis_index(axis)
        key = jax.random.fold_in(key, shard)
        pstate = TrainState(
            step=step_c, params=params, batch_stats=batch_stats,
            opt_state=opt_local, apply_fn=apply_fn, tx=tx,
        )
        # -- local microbatch accumulation (mirrors the fp32 path) --------
        if grad_accum == 1:
            g, l, m, stats, w = micro_grads_local(
                pstate, batch, jax.random.fold_in(key, 0)
            )
            w0 = as_weight(w)
            grads = jax.tree_util.tree_map(lambda x: x * w0, g)
            loss, wsum = l * w0, w0
            metrics = jax.tree_util.tree_map(lambda x: x * w0, m)
        else:
            def split(x):
                a = x.shape[0] // grad_accum
                return x.reshape(grad_accum, a, *x.shape[1:])

            micro = jax.tree_util.tree_map(split, batch)
            first = jax.tree_util.tree_map(lambda x: x[0], micro)
            rest = jax.tree_util.tree_map(lambda x: x[1:], micro)
            g, l, m, stats, w = micro_grads_local(
                pstate, first, jax.random.fold_in(key, 0)
            )
            w0 = as_weight(w)
            grads = jax.tree_util.tree_map(lambda x: x * w0, g)
            loss = l * w0
            metrics = jax.tree_util.tree_map(lambda x: x * w0, m)

            def scan_body(carry, inp):
                grads_sum, loss_sum, metrics_sum, wsum, stats = carry
                i, mb = inp
                st = pstate.replace(batch_stats=stats)
                gi, li, mi, stats, wi = micro_grads_local(
                    st, mb, jax.random.fold_in(key, i)
                )
                wi = as_weight(wi)
                return (
                    jax.tree_util.tree_map(
                        lambda a, b: a + b * wi, grads_sum, gi),
                    loss_sum + li * wi,
                    jax.tree_util.tree_map(
                        lambda a, b: a + b * wi, metrics_sum, mi),
                    wsum + wi,
                    stats,
                ), None

            idx = jnp.arange(1, grad_accum)
            (grads, loss, metrics, wsum, stats), _ = jax.lax.scan(
                scan_body, (grads, loss, metrics, w0, stats), (idx, rest)
            )

        # -- the exchange: one packed fp32 psum (small leaves + scalars), --
        # -- one quantized all-reduce (everything else)                   --
        grads_l, gdef = jax.tree_util.tree_flatten(grads)
        res_l = jax.tree_util.tree_flatten(residual)[0]
        big_g = [g for g, c in zip(grads_l, mask_leaves) if c]
        big_r = [r for r, c in zip(res_l, mask_leaves) if c]
        small_g = [g for g, c in zip(grads_l, mask_leaves) if not c]
        res_sq = sum(
            (jnp.sum(jnp.square(r)) for r in big_r),
            jnp.zeros((), jnp.float32),
        )
        mkeys = sorted(metrics)
        stats_l, stats_def = jax.tree_util.tree_flatten(stats)
        aux = (list(small_g) + [loss, wsum, res_sq]
               + [metrics[k] for k in mkeys] + list(stats_l))
        aux = comms_lib.psum_packed(aux, axis)
        ns_small = len(small_g)
        small_sum = aux[:ns_small]
        loss_g, wsum_g, res_sq_g = aux[ns_small:ns_small + 3]
        moff = ns_small + 3
        metrics_g = aux[moff:moff + len(mkeys)]
        stats_g = [s / nshards for s in aux[moff + len(mkeys):]]

        # wsum == 0 (every microbatch weightless on every shard) must give
        # the clean zero-gradient update, same as the fp32 path
        inv = 1.0 / jnp.where(wsum_g > 0, wsum_g, 1.0)
        metrics_out = {k: v * inv for k, v in zip(mkeys, metrics_g)}
        new_stats = jax.tree_util.tree_unflatten(stats_def, stats_g)

        if zlay is not None:
            # -- ZeRO owner-chunk update (parallel/zero.py): reduce-
            # SCATTER the mean gradient, update only this replica's 1/N
            # packed slice (optimizer state is the matching local slice),
            # then all-gather updated params — the gradient all-gather of
            # the replicated path becomes a param all-gather, whose
            # payload also carries each chunk's squared grad-norm.
            idx = jax.lax.axis_index(axis)
            cb, cs = zlay.chunk_big, zlay.chunk_small
            if big_g:
                gvec, _ = comms_lib.pack([g * inv for g in big_g])
                if cfg.transport == "int8":
                    rvec, rshapes = comms_lib.pack(big_r)
                    g_chunk, new_rvec, overflow = comms_lib.int8_scatter(
                        gvec, rvec, cfg, axis, nshards,
                        rng=(jax.random.fold_in(key, grad_accum)
                             if cfg.stochastic else None),
                    )
                    new_big_r = comms_lib.unpack(new_rvec, rshapes)
                else:
                    gvec = jnp.pad(
                        gvec, (0, zlay.padded_big - gvec.shape[0])
                    )
                    g_chunk = jax.lax.psum_scatter(
                        gvec, axis, scatter_dimension=0, tiled=True
                    )
                    overflow = jnp.zeros((), jnp.float32)
                    new_big_r = list(big_r)
            else:
                g_chunk = jnp.zeros((cb,), jnp.float32)
                overflow = jnp.zeros((), jnp.float32)
                new_big_r = []
            svec, _ = comms_lib.pack([s * inv for s in small_sum])
            svec = jnp.pad(svec, (0, zlay.padded_small - svec.shape[0]))
            s_chunk = jax.lax.dynamic_slice_in_dim(svec, idx * cs, cs)
            pb_vec, ps_vec = zero_lib.segment_vectors(params, zlay)
            g_chunks = {
                zero_lib.BIG: g_chunk[None],
                zero_lib.SMALL: s_chunk[None],
            }
            p_chunks = {
                zero_lib.BIG: jax.lax.dynamic_slice_in_dim(
                    pb_vec, idx * cb, cb)[None],
                zero_lib.SMALL: jax.lax.dynamic_slice_in_dim(
                    ps_vec, idx * cs, cs)[None],
            }
            new_p_chunks, new_opt = pstate.apply_chunk_gradients(
                g_chunks, p_chunks
            )
            gnorm_sq = (jnp.sum(jnp.square(g_chunk))
                        + jnp.sum(jnp.square(s_chunk)))
            payload = jnp.concatenate([
                new_p_chunks[zero_lib.BIG].reshape(-1),
                new_p_chunks[zero_lib.SMALL].reshape(-1),
                gnorm_sq[None],
            ])
            full = jax.lax.all_gather(payload, axis, tiled=True)
            full = full.reshape(nshards, cb + cs + 1)
            new_params = zero_lib.unpack_params(
                full[:, :cb].reshape(-1),
                full[:, cb:cb + cs].reshape(-1),
                zlay,
            )
            grad_norm = jnp.sqrt(jnp.sum(full[:, -1]))
            if residual is None:
                new_residual = None
            else:
                new_res_l, bi = [], 0
                for r, c in zip(res_l, mask_leaves):
                    if c:
                        new_res_l.append(new_big_r[bi])
                        bi += 1
                    else:
                        new_res_l.append(r)
                new_residual = jax.tree_util.tree_unflatten(gdef, new_res_l)
            return (new_params, new_opt, loss_g * inv, metrics_out,
                    new_stats, new_residual, overflow,
                    jnp.sqrt(res_sq_g), grad_norm)

        if big_g:
            gvec, gshapes = comms_lib.pack(
                [g * inv for g in big_g]
            )
            rvec, _ = comms_lib.pack(big_r)
            out_vec, new_rvec, overflow = comms_lib.int8_reduce(
                gvec, rvec, cfg, axis, nshards,
                rng=(jax.random.fold_in(key, grad_accum)
                     if cfg.stochastic else None),
            )
            big_out = comms_lib.unpack(out_vec, gshapes)
            new_big_r = comms_lib.unpack(new_rvec, gshapes)
        else:
            overflow = jnp.zeros((), jnp.float32)
            big_out, new_big_r = [], []

        out_l, new_res_l, bi, si = [], [], 0, 0
        for r, c in zip(res_l, mask_leaves):
            if c:
                out_l.append(big_out[bi])
                new_res_l.append(new_big_r[bi])
                bi += 1
            else:
                out_l.append(small_sum[si] * inv)
                new_res_l.append(r)
                si += 1
        grads_mean = jax.tree_util.tree_unflatten(gdef, out_l)
        new_residual = jax.tree_util.tree_unflatten(gdef, new_res_l)
        return (grads_mean, loss_g * inv, metrics_out, new_stats,
                new_residual, overflow, jnp.sqrt(res_sq_g))

    def step(state: TrainState, batch, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        for leaf in jax.tree_util.tree_leaves(batch):
            n = leaf.shape[0]
            if n % (grad_accum * nshards):
                raise ValueError(
                    f"global batch {n} not divisible by grad_accum="
                    f"{grad_accum} x {nshards} data shards"
                )
        batch_specs = jax.tree_util.tree_map(
            lambda l: P(axis, *(None,) * (l.ndim - 1)), batch
        )
        if zlay is None:
            exchanged = compat.shard_map(
                lambda s, p, bs, r, b, k: body(s, p, bs, (), r, b, k),
                mesh,
                in_specs=(P(), P(), P(), P(), batch_specs, P()),
                out_specs=P(),
                check_vma=False,  # the residual is deliberately device-varying
            )(state.step, state.params, state.batch_stats,
              state.comm_residual, batch, step_rng)
            grads, loss, metrics, new_stats, new_residual, overflow, res_norm = (
                exchanged
            )
            new_state = state.apply_gradients(
                grads, new_batch_stats=new_stats,
                new_comm_residual=new_residual
            )
            metrics = dict(metrics)
            metrics.setdefault("grad_norm", optax.global_norm(grads))
            metrics["comm_residual_norm"] = res_norm
            metrics["comm_overflow"] = overflow
            return new_state, {"loss": loss, **metrics}

        # sharded update: params/opt emerge from the shard_map already
        # final — no apply_gradients outside (the update ran on-chunk)
        opt_specs = zero_lib.opt_state_spec(state.opt_state, axis, nshards)
        outs = compat.shard_map(
            body, mesh,
            in_specs=(P(), P(), P(), opt_specs, P(), batch_specs, P()),
            out_specs=(P(), opt_specs, P(), P(), P(), P(), P(), P(), P()),
            check_vma=False,  # the residual is deliberately device-varying
        )(state.step, state.params, state.batch_stats, state.opt_state,
          state.comm_residual, batch, step_rng)
        (new_params, new_opt, loss, metrics, new_stats, new_residual,
         overflow, res_norm, grad_norm) = outs
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt,
            comm_residual=new_residual,
        )
        metrics = dict(metrics)
        metrics.setdefault("grad_norm", grad_norm)
        if cfg.transport == "int8":
            metrics["comm_residual_norm"] = res_norm
            metrics["comm_overflow"] = overflow
        return new_state, {"loss": loss, **metrics}

    return step


def _export_comm_gauges(state: TrainState, cfg, nshards: int) -> None:
    """Publish the analytic wire-byte accounting as comm/* gauges — set
    once at step-build time (the numbers are static per model x config)."""
    from tfde_tpu.observability import metrics as obs_metrics

    opt_sharding = "shard" if state.opt_layout is not None else "replicated"
    b = comms_lib.comm_bytes(state.params, cfg, nshards,
                             opt_sharding=opt_sharding)
    reg = obs_metrics.default_registry()
    reg.gauge("comm/bytes_per_step_fp32").set(b["fp32"])
    reg.gauge("comm/bytes_per_step_int8").set(b["int8"])
    reg.gauge("comm/compression_ratio").set(b["ratio"])
    reg.gauge("comm/compressed_elems").set(b["compressed_elems"])
    reg.gauge("comm/fp32_elems").set(b["fp32_elems"])


def _export_opt_gauges(state: TrainState) -> None:
    """Publish the weight-update-sharding memory/wire accounting as opt/*
    gauges: per-device optimizer-state bytes (the ~N x saving the ZeRO
    layout buys) and the trailing param all-gather's wire bytes (0 when
    replicated — there is no gather). Static per model x config, set once
    at step-build time.

    ``opt/state_bytes`` is MEASURED from the arrays XLA actually
    allocated (per-device shard bytes, parallel/zero.py
    measured_state_bytes); the shape-derived number stays published as
    ``opt/state_bytes_analytic`` for cross-check — a drift between the
    two is a padding or layout bug."""
    from tfde_tpu.observability import metrics as obs_metrics

    reg = obs_metrics.default_registry()
    analytic = zero_lib.state_bytes(state.opt_state, state.opt_layout)
    measured = zero_lib.measured_state_bytes(state.opt_state)
    reg.gauge("opt/state_bytes").set(measured if measured else analytic)
    reg.gauge("opt/state_bytes_analytic").set(analytic)
    reg.gauge("opt/param_gather_bytes").set(
        zero_lib.param_gather_bytes(state.opt_layout)
    )


def make_train_step(strategy: Strategy, state: TrainState, donate: bool = True,
                    grad_accum: int = 1, sentry=None, comms=None,
                    opt_sharding=None):
    """Compile train_step with the strategy's shardings pinned. `grad_accum`
    splits the batch into that many sequential microbatches per update (see
    make_custom_train_step). `sentry` (a SentryConfig) fuses the numerics
    check into the compiled step; the returned callable then takes and
    returns an extra sentry-state pytree: (state, batch, rng, sstate) ->
    (state, metrics, sstate). `comms` overrides the strategy's
    grad_transport knob (parallel/comms.py); int8 routes through the
    custom-step machinery, fp32 is byte-identical to always.
    `opt_sharding` overrides the strategy's weight-update-sharding knob
    (parallel/zero.py); a sharded (packed-opt) state routes through the
    custom-step machinery too."""
    cfg = _resolve_comms(strategy, state, comms)
    if (grad_accum != 1 or cfg.transport == "int8"
            or _resolve_opt_sharding(strategy, state, opt_sharding)):
        return make_custom_train_step(
            strategy, state, _classification_loss, donate=donate,
            grad_accum=grad_accum, sentry=sentry, comms=cfg,
            opt_sharding=opt_sharding,
        )
    _export_opt_gauges(state)
    shardings = _state_shardings(strategy, state)
    batch_sh = strategy.batch_sharding()
    if sentry is None:
        return jax.jit(
            _with_mesh(train_step, strategy.mesh),
            in_shardings=(shardings, (batch_sh, batch_sh), None),
            out_shardings=(shardings, None),
            donate_argnums=(0,) if donate else (),
        )
    rep = NamedSharding(strategy.mesh, P())  # sentry carry: tiny, replicated
    return jax.jit(
        _with_mesh(_sentried(train_step, sentry), strategy.mesh),
        in_shardings=(shardings, (batch_sh, batch_sh), None, rep),
        out_shardings=(shardings, None, rep),
        donate_argnums=(0,) if donate else (),
    )


def make_custom_train_step(
    strategy: Strategy,
    state: TrainState,
    loss_fn: Callable[[TrainState, Any, Any, jax.Array], Tuple[jax.Array, dict]],
    donate: bool = True,
    grad_accum: int = 1,
    sentry=None,
    comms=None,
    opt_sharding=None,
):
    """Compile a train step with a user loss over an arbitrary batch pytree.

    The generalization of `make_train_step` for objectives beyond
    (images, labels) classification — MLM, seq2seq, contrastive — the analog
    of the reference's hand-written `model_fn` path
    (tf2_mnist_distributed.py:65-91), where the user owns the loss and the
    framework owns differentiation, sharding, and the optimizer update.

    `loss_fn(state, params, batch, rng) -> (scalar_loss, metrics_dict)`.
    Models with BatchNorm return updated stats under the reserved metrics key
    ``"batch_stats"``. Every batch leaf must be [global_batch, ...]; each is
    sharded over the mesh's data axes.

    `grad_accum=A` splits the global batch into A sequential microbatches
    inside the SAME compiled step (`lax.scan`), averaging gradients before
    the single optimizer update — activation memory drops ~A-fold while the
    update matches the full-batch step exactly (BatchNorm stats chain
    through the microbatches in order). For losses normalized by a
    data-dependent denominator (e.g. masked-LM CE over the masked-position
    count), a uniform average of microbatch gradients would be a
    mean-of-means; return that denominator under the reserved metrics key
    ``"grad_weight"`` and the accumulation weights each microbatch by it
    (gradients, loss, and metrics), restoring the exact full-batch update.
    The reserved key ``"grad_norm"`` is emitted automatically (global norm
    of the final averaged gradients); a loss_fn returning its own
    ``grad_norm`` metric takes precedence.
    The standard route to reference-scale global batches on few chips.

    `comms` selects the gradient transport (parallel/comms.py): None reads
    the strategy's grad_transport knob; 'fp32' (the default everywhere) is
    byte-identical to the historical path; 'int8' swaps the step body for
    the quantized exchange with error feedback — compression happens once
    per update, after grad accumulation.

    `opt_sharding` selects the weight-update layout (parallel/zero.py):
    None reads the strategy's knob; 'replicated' (the default) keeps every
    replica updating the full params; a state whose optimizer state was
    built sharded ('shard' at init_state) routes through the same
    explicit-exchange body as int8, with the update run on each replica's
    owned 1/N chunk and updated params all-gathered — composing with both
    transports inside the five-collective budget.
    """
    shardings = _state_shardings(strategy, state)
    batch_sh = strategy.batch_sharding()
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    ccfg = _resolve_comms(strategy, state, comms)
    zshard = _resolve_opt_sharding(strategy, state, opt_sharding)

    def micro_grads(state: TrainState, batch, rng):
        def wrapped(params):
            return loss_fn(state, params, batch, rng)

        (loss, metrics), grads = jax.value_and_grad(wrapped, has_aux=True)(
            state.params
        )
        metrics = dict(metrics)
        new_stats = metrics.pop("batch_stats", state.batch_stats)
        weight = metrics.pop("grad_weight", None)
        return grads, loss, metrics, new_stats, weight

    def step(state: TrainState, batch, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        if grad_accum == 1:
            grads, loss, metrics, new_stats, _ = micro_grads(
                state, batch, step_rng
            )
            new_state = state.apply_gradients(grads, new_batch_stats=new_stats)
            metrics.setdefault("grad_norm", optax.global_norm(grads))
            return new_state, {"loss": loss, **metrics}

        b = axes_lib.batch_axes()
        from tfde_tpu.parallel.sharding import data_axes as _data_axes

        d_shards = 1
        for a in _data_axes(strategy.mesh):
            d_shards *= strategy.mesh.shape[a]

        def split(x):
            n = x.shape[0]
            if n % (grad_accum * d_shards):
                raise ValueError(
                    f"global batch {n} not divisible by grad_accum="
                    f"{grad_accum} x {d_shards} data shards"
                )
            m = n // (grad_accum * d_shards)
            # device-major split: microbatch i takes the i-th sub-chunk of
            # every device's local shard, so the [B] -> [A, B/A] reshape is
            # local to each device (a microbatch-major reshape would cut
            # across shard boundaries and force SPMD to replicate the batch
            # — "involuntary full rematerialization"). Microbatch membership
            # is exchangeable; the accumulated gradient is identical.
            x = x.reshape(d_shards, grad_accum, m, *x.shape[1:])
            x = jnp.swapaxes(x, 0, 1)
            x = x.reshape(grad_accum, d_shards * m, *x.shape[3:])
            # microbatches keep the data sharding on their own batch dim
            return axes_lib.constrain(x, None, b)

        micro = jax.tree_util.tree_map(split, batch)
        first = jax.tree_util.tree_map(lambda x: x[0], micro)
        rest = jax.tree_util.tree_map(lambda x: x[1:], micro)

        def as_weight(w):
            return (jnp.ones((), jnp.float32) if w is None
                    else jnp.asarray(w, jnp.float32))

        # microbatch 0 eagerly — its (grads, loss, metrics) fix the carry
        # structure for the scan over microbatches 1..A-1
        grads, loss, metrics, stats, w = micro_grads(
            state, first, jax.random.fold_in(step_rng, 0)
        )
        w0 = as_weight(w)
        grads = jax.tree_util.tree_map(lambda g: g * w0, grads)
        loss = loss * w0
        metrics = jax.tree_util.tree_map(lambda m: m * w0, metrics)

        def body(carry, inp):
            grads_sum, loss_sum, metrics_sum, wsum, stats = carry
            i, mb = inp
            st = state.replace(batch_stats=stats)
            g, l, m, stats, w = micro_grads(
                st, mb, jax.random.fold_in(step_rng, i)
            )
            wi = as_weight(w)
            return (
                jax.tree_util.tree_map(lambda a, b: a + b * wi, grads_sum, g),
                loss_sum + l * wi,
                jax.tree_util.tree_map(lambda a, b: a + b * wi, metrics_sum, m),
                wsum + wi,
                stats,
            ), None

        idx = jnp.arange(1, grad_accum)
        (grads, loss, metrics, wsum, stats), _ = jax.lax.scan(
            body, (grads, loss, metrics, w0, stats), (idx, rest)
        )
        # wsum == 0 (every microbatch weightless, e.g. an all-IGNORE MLM
        # batch) must yield the accum=1 behavior — a clean zero-gradient
        # update — not 0 * inf = NaN params; any positive wsum divides exactly
        inv = 1.0 / jnp.where(wsum > 0, wsum, 1.0)
        grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
        loss = loss * inv
        metrics = jax.tree_util.tree_map(lambda m: m * inv, metrics)
        new_state = state.apply_gradients(grads, new_batch_stats=stats)
        metrics["grad_norm"] = metrics.get(
            "grad_norm", optax.global_norm(grads)
        )
        return new_state, {"loss": loss, **metrics}

    if ccfg.transport == "int8" or zshard:
        # swap the whole step body: local grads + explicit exchange
        # (quantized and/or owner-chunk-updated) instead of the
        # partitioner's implicit fp32 psum + replicated update. The fp32
        # `step` above is never traced, so the default path's jaxpr stays
        # byte-identical.
        step = _make_comms_step(strategy, state, loss_fn, ccfg, grad_accum)
        _export_comm_gauges(
            state, ccfg,
            int(strategy.mesh.shape[comms_lib.data_axis(strategy.mesh)]),
        )
    _export_opt_gauges(state)

    def batch_shardings(batch):
        return jax.tree_util.tree_map(lambda _: batch_sh, batch)

    if sentry is None:
        jitted = jax.jit(
            _with_mesh(step, strategy.mesh),
            in_shardings=(shardings, None, None),  # batch via device_put
            out_shardings=(shardings, None),
            donate_argnums=(0,) if donate else (),
        )

        def run(state: TrainState, batch, rng):
            batch = jax.device_put(batch, batch_shardings(batch))
            return jitted(state, batch, rng)
    else:
        rep = NamedSharding(strategy.mesh, P())  # sentry carry: replicated
        jitted = jax.jit(
            _with_mesh(_sentried(step, sentry), strategy.mesh),
            in_shardings=(shardings, None, None, rep),
            out_shardings=(shardings, None, rep),
            donate_argnums=(0,) if donate else (),
        )

        def run(state: TrainState, batch, rng, sstate):
            batch = jax.device_put(batch, batch_shardings(batch))
            return jitted(state, batch, rng, sstate)

    run.jitted = jitted  # the lower()/jaxpr inspection hook (tests)
    run.lower = jitted.lower  # quacks like the jitted fast path for guards
    return run


def make_custom_eval_step(
    strategy: Strategy,
    state: TrainState,
    eval_fn: Callable[[TrainState, Any, Any], dict],
):
    """Compile a weighted-metrics eval step for a user metric fn — the eval
    twin of make_custom_train_step (the Estimator's custom-objective path).

    `eval_fn(state, params, batch) -> {metric: per-batch mean}`; an optional
    reserved key ``"weight"`` carries the batch's aggregation weight (e.g.
    the masked-position count for MLM metrics; defaults to the batch size).
    The returned step emits weighted SUMS plus the weight, so the caller
    accumulates on device and divides once after the pass — the same
    one-fetch protocol as the classification eval_step."""
    shardings = _state_shardings(strategy, state)
    batch_sh = strategy.batch_sharding()

    def step(state: TrainState, batch):
        metrics = dict(eval_fn(state, state.params, batch))
        weight = metrics.pop("weight", None)
        if weight is None:
            leaf = jax.tree_util.tree_leaves(batch)[0]
            weight = jnp.asarray(float(leaf.shape[0]), jnp.float32)
        weight = jnp.asarray(weight, jnp.float32)
        out = {k: jnp.asarray(v, jnp.float32) * weight
               for k, v in metrics.items()}
        out["weight"] = weight
        return out

    jitted = jax.jit(
        _with_mesh(step, strategy.mesh),
        in_shardings=(shardings, None),
    )

    def run(state: TrainState, batch):
        batch = jax.device_put(
            batch, jax.tree_util.tree_map(lambda _: batch_sh, batch)
        )
        return jitted(state, batch)

    return run


def make_eval_step(strategy: Strategy, state: TrainState):
    shardings = _state_shardings(strategy, state)
    batch_sh = strategy.batch_sharding()
    return jax.jit(
        _with_mesh(eval_step, strategy.mesh),
        in_shardings=(shardings, (batch_sh, batch_sh, batch_sh)),
    )


def pad_batch_for_mesh(
    batch: Tuple, divisor: int
) -> Tuple[Any, Any, Any]:
    """Pad (images, labels) up to a multiple of the mesh batch divisor and
    append the validity mask consumed by eval_step."""
    import numpy as np

    images, labels = batch[0], batch[1]
    n = images.shape[0]
    padded = -(-n // divisor) * divisor
    mask = np.zeros((padded,), np.float32)
    mask[:n] = 1.0
    if padded != n:
        pad = [(0, padded - n)] + [(0, 0)] * (images.ndim - 1)
        images = np.pad(np.asarray(images), pad)
        labels = np.pad(np.asarray(labels), [(0, padded - n)] + [(0, 0)] * (labels.ndim - 1))
    return images, labels, mask
