"""Training lifecycle: train state, compiled steps, and the Estimator-style
train-and-evaluate driver."""

from tfde_tpu.training.train_state import TrainState  # noqa: F401
from tfde_tpu.training.step import make_train_step, make_eval_step, init_state  # noqa: F401
from tfde_tpu.training.optimizers import (  # noqa: F401
    adamw,
    ema_params,
    with_param_ema,
)
from tfde_tpu.training.lora import (  # noqa: F401
    LoraConfig,
    init_lora,
    init_lora_state,
    make_lora_loss,
    merge_lora,
)
from tfde_tpu.training.lifecycle import (  # noqa: F401
    Estimator,
    RunConfig,
    TrainSpec,
    EvalSpec,
    continuous_eval,
    train_and_evaluate,
)
