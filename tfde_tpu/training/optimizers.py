"""Optimizer helpers — the transformer-training conventions optax leaves
to the user.

`adamw(...)` here is optax.adamw with the standard decay mask: weight decay
applies to matmul kernels and embeddings only — biases and normalization
scales are excluded (the BERT/GPT-2 convention; decaying a LayerNorm scale
toward zero fights the normalization itself). The mask is derived from the
param tree: any leaf whose path ends in 'bias' or whose name is a norm
scale ('scale') is excluded, plus any rank-<2 leaf as a conservative
fallback (a rank-1 tensor in a transformer is a bias/scale/norm by
construction; kernels and embeddings are rank >= 2)."""

from __future__ import annotations

import jax
import optax


def decay_mask(params) -> object:
    """Pytree of bools: True where weight decay applies."""

    def keep(path, leaf) -> bool:
        names = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        if names and names[-1] in ("bias", "scale"):
            return False
        return jax.numpy.ndim(leaf) >= 2

    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves = [keep(p, l) for p, l in flat[0]]
    return jax.tree_util.tree_unflatten(flat[1], leaves)


def adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
) -> optax.GradientTransformation:
    """optax.adamw with decay masked off biases/norm scales (see module
    docstring). Drop-in for the examples' optax.adamw calls."""
    return optax.adamw(
        learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
        mask=decay_mask,
    )
