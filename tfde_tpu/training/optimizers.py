"""Optimizer helpers — the transformer-training conventions optax leaves
to the user.

`adamw(...)` here is optax.adamw with the standard decay mask: weight decay
applies to matmul kernels and embeddings only — biases and normalization
scales are excluded (the BERT/GPT-2 convention; decaying a LayerNorm scale
toward zero fights the normalization itself). The mask is derived from the
param tree: any leaf whose path ends in 'bias' or whose name is a norm
scale ('scale') is excluded, plus any rank-<2 leaf as a conservative
fallback (a rank-1 tensor in a transformer is a bias/scale/norm by
construction; kernels and embeddings are rank >= 2)."""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import optax


def decay_mask(params) -> object:
    """Pytree of bools: True where weight decay applies."""

    def keep(path, leaf) -> bool:
        names = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        if names and names[-1] in ("bias", "scale"):
            return False
        return jax.numpy.ndim(leaf) >= 2

    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves = [keep(p, l) for p, l in flat[0]]
    return jax.tree_util.tree_unflatten(flat[1], leaves)


def adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
) -> optax.GradientTransformation:
    """optax.adamw with decay masked off biases/norm scales (see module
    docstring). Drop-in for the examples' optax.adamw calls."""
    return optax.adamw(
        learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
        mask=decay_mask,
    )


class ParamEmaState(NamedTuple):
    """Polyak/EMA copy of the post-update params, riding the optimizer
    state (with_param_ema)."""

    ema: Any


def with_param_ema(tx: optax.GradientTransformation,
                   decay: float = 0.999) -> optax.GradientTransformation:
    """Wrap an optimizer so an exponential moving average of the
    POST-update params rides the optimizer state:

        ema <- decay * ema + (1 - decay) * (params + updates)

    Evaluating/serving on the averaged weights is the standard
    late-training variance reducer. The average initializes at the
    initial params (the TF ExponentialMovingAverage convention, no
    zero-debias), so it needs ~3/(1-decay) steps to forget the random
    init — pick decay against the run length (0.999 suits multi-thousand
    -step runs; a 150-step smoke test wants 0.9). Living in opt_state
    means the EMA is
    checkpointed with everything else (resume keeps it) and SHARDED like
    the params automatically — strategies map any params-shaped opt_state
    subtree to the param specs (parallel/strategies.opt_state_spec), so
    FSDP/TP lay the copy out alongside the live weights. Extract with
    `ema_params(state.opt_state)` and evaluate via
    `state.replace(params=...)`.
    """
    if not 0.0 <= decay < 1.0:
        raise ValueError(f"decay must be in [0, 1), got {decay}")

    def init(params):
        return (tx.init(params), ParamEmaState(ema=params))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError(
                "with_param_ema needs params at update time (optax "
                "passes them when the caller supplies params= — "
                "training/step.py does)"
            )
        inner, ema_state = state
        updates, inner = tx.update(updates, inner, params)
        new_ema = jax.tree_util.tree_map(
            lambda e, p, u: decay * e + (1.0 - decay) * (p + u),
            ema_state.ema, params, updates,
        )
        return updates, (inner, ParamEmaState(ema=new_ema))

    return optax.GradientTransformation(init, update)


def ema_params(opt_state):
    """The EMA params tree from a `with_param_ema` optimizer state, found
    structurally (works however deep the wrapper sits in an optax
    chain)."""
    found = []

    def walk(node):
        if isinstance(node, ParamEmaState):
            found.append(node.ema)
            return
        if isinstance(node, (tuple, list)):
            for c in node:
                walk(c)
        elif isinstance(node, dict):
            for c in node.values():
                walk(c)

    walk(opt_state)
    if len(found) != 1:
        raise ValueError(
            f"expected exactly one ParamEmaState in the optimizer state, "
            f"found {len(found)} — was the optimizer built with "
            f"with_param_ema?"
        )
    return found[0]
