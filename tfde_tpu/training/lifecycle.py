"""Estimator-style training lifecycle: train_and_evaluate with eval
throttling, periodic checkpoint/summaries, resume-by-default, final export.

Re-specifies explicitly the implicit `tf.estimator.train_and_evaluate`
behavior the reference relies on (SURVEY.md §7 "Estimator-lifecycle
fidelity"): TrainSpec.max_steps bounds training (mnist_keras:255-262);
EvalSpec runs the *full* eval set when steps=None, no earlier than
start_delay_secs after start and at most every throttle_secs (mnist_keras:
264-275); checkpoints every RunConfig.save_checkpoints_steps into model_dir
with transparent resume on restart (mnist_keras:245-248); scalar summaries
every save_summary_steps and steps/sec every log_step_count_steps
(mnist_keras:246-247); FinalExporter artifacts written at end of training
(mnist_keras:264; §3.4).

Differences from the reference, on purpose:
- train and eval interleave in one SPMD process group (every chip trains;
  eval is a compiled pass on the same mesh) instead of a separate eval
  cluster — there is no idle eval fleet on TPU.
- checkpoint saves are async (Orbax): the train loop never blocks on I/O.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu import knobs
from tfde_tpu.analysis import hlolint
from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.data.device import device_prefetch
from tfde_tpu.resilience.preemption import PreemptionGuard as _PreemptionGuard
from tfde_tpu.data.pipeline import AutoShardPolicy
from tfde_tpu.observability import aggregate, exposition, flightrec, metrics
from tfde_tpu.observability import memwatch
from tfde_tpu.observability import recompile
from tfde_tpu.observability import sentry as sentry_lib
from tfde_tpu.observability.goodput import GoodputLedger
from tfde_tpu.observability.profiler import StepWindowProfiler
from tfde_tpu.observability.spans import record, span
from tfde_tpu.observability.tensorboard import SummaryWriter
from tfde_tpu.parallel.strategies import Strategy, MultiWorkerMirroredStrategy
from tfde_tpu.training.step import (
    init_state,
    make_custom_eval_step,
    make_custom_train_step,
    make_train_step,
    make_eval_step,
    pad_batch_for_mesh,
)
from tfde_tpu.training.train_state import TrainState

log = logging.getLogger(__name__)


# _PreemptionGuard moved to tfde_tpu/resilience/preemption.py (PR 1): the
# supervisor and the stall watchdog share the same signal machinery, so it
# lives in the resilience layer; the alias import above keeps this module's
# train() and existing callers/tests unchanged.


@dataclasses.dataclass
class RunConfig:
    """Training-run configuration (tf.estimator.RunConfig analog,
    mnist_keras:240-248)."""

    model_dir: Optional[str] = None
    save_summary_steps: int = 100
    log_step_count_steps: int = 100
    # None/0 disables checkpointing (and resume) entirely — useful when the
    # model_dir is a filesystem the checkpoint backend doesn't support
    # (Orbax/tensorstore speak gs:// but not e.g. memory://), or for
    # throwaway runs. Summaries and export still honor model_dir.
    save_checkpoints_steps: Optional[int] = 500
    keep_checkpoint_max: int = 5
    # Profiler window(s) captured into <model_dir>/plugins/profile — the
    # reference's ProfilerHook capability (mnist_keras:235-237,261).
    # (start, stop) for one global-step window, or "every:N" / "every:N:S"
    # to re-trace S steps (default 10) every N steps the way
    # ProfilerHook(save_steps=100) did. None defers to $TFDE_PROFILE.
    profile_steps: Any = None
    seed: int = 0
    # Chief-only HTTP /metrics endpoint (observability/exposition.py):
    # 0 binds an ephemeral port (read estimator.metrics_server.port back),
    # None defers to $TFDE_METRICS_PORT (unset = no server). The chief's
    # server carries a ClusterAggregator, so worker pushes (below) show up
    # host-labelled in one scrape with straggler/staleness rollups.
    metrics_port: Optional[int] = None
    # Non-chief hosts POST periodic snapshots here (".../push"). None
    # derives it from the cluster spec: $TFDE_METRICS_PUSH_URL wins, else
    # the coordinator host + $TFDE_METRICS_PORT (runtime/cluster.py).
    metrics_push_url: Optional[str] = None
    metrics_push_interval: float = 5.0
    # Device-resident numerics sentry (observability/sentry.py):
    # None/False off, True = SentryConfig() defaults, or a SentryConfig.
    # Fused into the compiled train step — no extra dispatch; a NaN/Inf or
    # grad-norm blow-up raises NumericsError at the next poll window.
    sentry: Any = None
    # Gradient-exchange wire format (parallel/comms.py): 'fp32' (the
    # default, byte-identical to always), 'int8' (blockwise-quantized
    # all-reduce with error feedback — ~4x less gradient traffic on pure-DP
    # meshes), or a comms.CommsConfig for the threshold/block knobs. None
    # defers to the strategy's own grad_transport / $TFDE_GRAD_TRANSPORT.
    grad_transport: Any = None
    # Weight-update sharding (parallel/zero.py): 'replicated' (the default
    # — every device runs the full optimizer update), or 'shard' (ZeRO-1:
    # optimizer state partitioned over the data axis, each device updates
    # its 1/N chunk and all-gathers the result — ~N x less optimizer
    # memory on pure-DP meshes). None defers to the strategy's own
    # opt_sharding / $TFDE_OPT_SHARDING.
    opt_sharding: Any = None


@dataclasses.dataclass
class TrainSpec:
    """input_fn -> Dataset/iterable of (images, labels) host batches."""

    input_fn: Callable[[], Iterable]
    max_steps: int
    shard_policy: AutoShardPolicy = AutoShardPolicy.DATA


@dataclasses.dataclass
class EvalSpec:
    input_fn: Callable[[], Iterable]
    steps: Optional[int] = None  # None = full pass (mnist_keras:271)
    name: str = "eval"
    exporters: Sequence = ()
    start_delay_secs: float = 10.0
    throttle_secs: float = 10.0


class Estimator:
    """Owns model + optimizer + strategy + run config; train/evaluate/predict/
    export with checkpoint-resume (the tf.keras.estimator.model_to_estimator
    capability, mnist_keras:118-119, minus the Keras conversion detour)."""

    def __init__(
        self,
        model,
        optimizer,
        strategy: Optional[Strategy] = None,
        config: Optional[RunConfig] = None,
        eval_strategy: Optional[Strategy] = None,
        loss_fn=None,
        eval_fn=None,
        grad_accum: int = 1,
        lora=None,
        lora_base_params=None,
    ):
        """eval_strategy: evaluate under a *different* strategy than training
        — the reference's `DistributeConfig(train_distribute=
        ParameterServerStrategy, eval_distribute=MirroredStrategy)`
        (mnist_keras_distributed.py:241-243). Defaults to the training
        strategy. At eval time the train state is device_put onto the eval
        strategy's shardings and eval_step compiles on its mesh.

        loss_fn: a custom objective `(state, params, batch, rng) ->
        (loss, metrics)` (training/step.py make_custom_train_step) — the
        reference's hand-written model_fn path riding the FULL Estimator
        lifecycle (checkpoints/resume, summaries, eval cadence) instead of
        a hand-rolled loop. Token models (MLM, causal LM) go through here.
        eval_fn: its eval twin `(state, params, batch) -> {metric:
        per-batch mean}` (+ optional reserved "weight"); required for
        evaluate()/train_and_evaluate() when loss_fn is set — eval must be
        deterministic, which the rng-taking loss_fn cannot promise.
        grad_accum: sequential microbatches per update (step.py).

        lora + lora_base_params: parameter-efficient fine-tuning through
        the FULL lifecycle (training/lora.py). The TrainState — and so
        every checkpoint — holds only the rank-r adapters and their
        optimizer slots (tiny, fast saves); the frozen base is a constant
        of the compiled step. evaluate()/predict()/export run on the
        MERGED base-shaped params, so eval_fn, the serving signature, and
        exporters see a plain model. loss_fn/eval_fn keep their normal
        signatures (their `params` argument arrives merged)."""
        self.model = model
        self.tx = optimizer
        self.strategy = strategy or MultiWorkerMirroredStrategy()
        self.eval_strategy = eval_strategy
        self.loss_fn = loss_fn
        self.eval_fn = eval_fn
        self.grad_accum = grad_accum
        if (lora is None) != (lora_base_params is None):
            raise ValueError(
                "lora and lora_base_params come together: the LoraConfig "
                "says what to adapt, the base params are what stays frozen"
            )
        self.lora = lora
        self._lora_base = lora_base_params
        self.config = config or RunConfig()
        if self.config.grad_transport is not None:
            # RunConfig wins over the strategy's own knob — one switch
            # flips the transport for the whole run (init_state allocates
            # the error-feedback residual off the same strategy.comms)
            self.strategy.comms = self.config.grad_transport
        if self.config.opt_sharding is not None:
            # same precedence for the ZeRO knob: init_state decides the
            # packed-vs-replicated opt layout off strategy.opt_sharding
            self.strategy.opt_sharding = self.config.opt_sharding
        self._state: Optional[TrainState] = None
        self._ckpt: Optional[CheckpointManager] = None
        self._train_step = None
        self._eval_step = None
        self._predict_fn = None
        self._writers: dict[str, SummaryWriter] = {}
        self._metrics_srv: Optional[exposition.MetricsServer] = None
        self._metrics_log: Optional[exposition.JsonlMetricsLog] = None
        self._aggregator: Optional[aggregate.ClusterAggregator] = None
        self._pusher: Optional[aggregate.MetricsPusher] = None

    # -- internals -----------------------------------------------------------
    @property
    def _is_chief(self) -> bool:
        return jax.process_index() == 0

    def _writer(self, name: str = "") -> Optional[SummaryWriter]:
        if self.config.model_dir is None or not self._is_chief:
            return None
        if name not in self._writers:
            logdir = self.config.model_dir
            if name:
                logdir = f"{logdir}/{name}"
            self._writers[name] = SummaryWriter(logdir)
        return self._writers[name]

    @property
    def metrics_server(self) -> Optional[exposition.MetricsServer]:
        """The live /metrics endpoint, if one was configured and started."""
        return self._metrics_srv

    def _ensure_metrics_server(self) -> Optional[exposition.MetricsServer]:
        if self._metrics_srv is not None or not self._is_chief:
            return self._metrics_srv
        port = self.config.metrics_port
        if port is None:
            port = knobs.env_int("TFDE_METRICS_PORT")
        if port is not None:
            # include_local=0 folds the chief's own registry into every
            # rollup as host 0, so cluster medians cover the chief without
            # it HTTP-pushing to itself; single-process runs just see a
            # one-host "cluster"
            self._aggregator = aggregate.ClusterAggregator(include_local=0)
            self._metrics_srv = exposition.MetricsServer(
                port=port, aggregator=self._aggregator
            )
        return self._metrics_srv

    def _ensure_metrics_pusher(self) -> Optional[aggregate.MetricsPusher]:
        """Non-chief: start the periodic snapshot push to the chief's
        /push endpoint, if a push URL is configured or derivable."""
        if self._pusher is not None or self._is_chief:
            return self._pusher
        url = self.config.metrics_push_url
        if url is None:
            from tfde_tpu.runtime import cluster

            url = cluster.metrics_push_url()
        if url:
            self._pusher = aggregate.MetricsPusher(
                url, interval=self.config.metrics_push_interval,
                host=jax.process_index(),
            )
        return self._pusher

    def _ensure_metrics_log(self) -> Optional[exposition.JsonlMetricsLog]:
        """Chief-only JSONL snapshot log under <model_dir>/metrics/."""
        if self.config.model_dir is None or not self._is_chief:
            return None
        if self._metrics_log is None:
            self._metrics_log = exposition.JsonlMetricsLog(self.config.model_dir)
        return self._metrics_log

    def _ckpt_mngr(self) -> Optional[CheckpointManager]:
        if self.config.model_dir is None or not self.config.save_checkpoints_steps:
            return None
        if self._ckpt is None:
            self._ckpt = CheckpointManager(
                f"{self.config.model_dir}/checkpoints",
                max_to_keep=self.config.keep_checkpoint_max,
            )
        return self._ckpt

    def _ensure_state(self, sample_batch) -> TrainState:
        if self._state is None:
            # the model's sample input is the FIRST LEAF of the batch pytree
            # (tuple position 0; for dict batches, the first key in sorted
            # order) — the init contract for custom batch structures
            leaf = jax.tree_util.tree_leaves(sample_batch)[0]
            sample = jnp.zeros(np.asarray(leaf).shape, np.asarray(leaf).dtype)
            if self.lora is not None:
                from tfde_tpu.training.lora import init_lora_state

                # BatchNorm models carry mutable batch_stats the adapter
                # state doesn't hold — refuse loudly rather than crash
                # with a missing-collection error inside the jitted step
                abstract = jax.eval_shape(
                    self.model.init, jax.random.key(0), sample
                )
                if abstract.get("batch_stats"):
                    raise NotImplementedError(
                        "LoRA through the Estimator does not support "
                        "BatchNorm models yet (the frozen base's "
                        "batch_stats would need to thread through the "
                        "adapter state); fine-tune a norm-free model or "
                        "use the full-training path"
                    )
                self._lora_base = jax.device_put(
                    self._lora_base,
                    self.strategy.params_sharding(self._lora_base),
                )
                self._state, _ = init_lora_state(
                    self.model, self.tx, self.strategy, self._lora_base,
                    self.lora, seed=self.config.seed,
                )
            else:
                self._state, _ = init_state(
                    self.model, self.tx, self.strategy, sample,
                    seed=self.config.seed,
                )
            self._from_checkpoint = False
            mngr = self._ckpt_mngr()
            if mngr is not None:
                restored = mngr.restore_latest(self._state)
                if restored is not None:
                    self._state = restored  # resume-by-default (SURVEY.md §5)
                    self._from_checkpoint = True
        return self._state

    def _merged(self, state: TrainState) -> TrainState:
        """For evaluate/predict/export under LoRA: a base-shaped state with
        the adapters folded in (training/lora.merge_lora) — downstream
        paths (eval steps, serving signature, exporters) see a plain
        model. No-op otherwise."""
        if self.lora is None:
            return state
        from tfde_tpu.training.lora import merge_lora

        return state.replace(
            params=merge_lora(self._lora_base, state.params, self.lora)
        )

    def merged_params(self, sample_input=None):
        """Base-shaped params ready for serving/export: the LoRA adapters
        folded into the frozen base (plain params when LoRA is off);
        feeds save_converted / export_serving / generate directly.

        In a fresh process (nothing trained yet), pass `sample_input` — a
        model-input-shaped array, e.g. np.zeros((1, seq), np.int32) — and
        the state restores from model_dir's latest checkpoint the same
        way evaluate()/predict() would."""
        if self._state is None and sample_input is not None:
            # one restore-or-raise path for every inference entry point
            self._state_for_inference(lambda: [(sample_input,)],
                                      "merged_params()")
        if self._state is None:
            raise RuntimeError(
                "merged_params() before train(): no trained state in this "
                "process — train() first, or pass sample_input to restore "
                "from model_dir's latest checkpoint"
            )
        return self._merged(self._state).params

    def _state_for_inference(self, input_fn, what: str) -> TrainState:
        """State for evaluate/predict/export: live if this process trained,
        else restored from model_dir (the Estimator eval-from-checkpoint
        flow); error only when neither exists."""
        if self._state is not None:
            return self._state
        first = next(iter(input_fn()))
        state = self._ensure_state(first)
        if not self._from_checkpoint:
            self._state = None  # don't let later train() skip resume logic
            raise RuntimeError(
                f"{what} before train(): no trained state in this process and "
                f"no checkpoint found in model_dir={self.config.model_dir!r}"
            )
        return state

    # -- train ---------------------------------------------------------------
    def train(
        self,
        input_fn: Callable[[], Iterable],
        max_steps: int,
        shard_policy: AutoShardPolicy = AutoShardPolicy.DATA,
        _eval_hook: Optional[Callable[[TrainState, int], None]] = None,
    ) -> TrainState:
        """Train until global step reaches max_steps (TrainSpec semantics:
        max_steps is absolute, so a resumed run does only the remainder —
        matching Estimator's behavior with mnist_keras:262)."""
        cfg = self.config
        ledger = GoodputLedger()  # baseline first: init counts toward wall
        self._ensure_metrics_server()
        self._ensure_metrics_pusher()
        scfg = sentry_lib.resolve(cfg.sentry)
        if cfg.model_dir is not None:
            # arm BEFORE the PreemptionGuard below: the guard saves this
            # handler as "previous", so after the guard's force-save commits
            # and the signal re-raises, the ring dumps on the way out
            flightrec.arm(cfg.model_dir)
        with span("train/init"):
            host_iter = iter(input_fn())
            first = next(host_iter)
            state = self._ensure_state(first)
            start_step = int(jax.device_get(state.step))
            if start_step >= max_steps:
                log.info("global step %d >= max_steps %d; nothing to do",
                         start_step, max_steps)
                return state
            if self._train_step is None:
                if self.lora is not None:
                    from tfde_tpu.training.lora import make_lora_loss
                    from tfde_tpu.training.step import _classification_loss

                    self._train_step = make_custom_train_step(
                        self.strategy, state,
                        make_lora_loss(self._lora_base,
                                       self.loss_fn or _classification_loss,
                                       self.lora),
                        grad_accum=self.grad_accum, sentry=scfg,
                    )
                elif self.loss_fn is not None:
                    self._train_step = make_custom_train_step(
                        self.strategy, state, self.loss_fn,
                        grad_accum=self.grad_accum, sentry=scfg,
                    )
                else:
                    self._train_step = make_train_step(
                        self.strategy, state, grad_accum=self.grad_accum,
                        sentry=scfg,
                    )

        rng = jax.random.key(cfg.seed + 1)
        with span("train/init"):  # second init chunk: writers/manager/feed
            writer = self._writer()
            mngr = self._ckpt_mngr()
            from tfde_tpu.observability import profiler as profiler_lib

            artifacts = (
                profiler_lib.ProfileArtifacts(cfg.model_dir)
                if self._is_chief and cfg.model_dir is not None else None
            )
            profiler = (
                StepWindowProfiler(cfg.model_dir, cfg.profile_steps,
                                   artifacts=artifacts)
                if self._is_chief
                else StepWindowProfiler(None, None)
            )
            # hub registration: SLO-burn/straggler/recompile-storm triggers
            # can now arm a bounded step-window capture on this run
            profiler_lib.hub().register("train_step_window",
                                        profiler.trigger_sink)

            def batches():
                yield first
                yield from host_iter

            feed = device_prefetch(batches(), self.strategy.mesh,
                                   policy=shard_policy,
                                   wait_metric="train/data_wait")
            mlog = self._ensure_metrics_log()
            ops_writer = self._writer("ops") if writer is not None else None
            # sentry carry lives ON DEVICE; the monitor polls one scalar
            # every poll_every steps — the sentry's entire host-side cost
            monitor = (sentry_lib.SentryMonitor(scfg, profiler=profiler)
                       if scfg is not None else None)
            sstate = sentry_lib.init_state() if scfg is not None else None
        flightrec.record("train_start", start_step=start_step,
                         max_steps=max_steps,
                         resumed=bool(self._from_checkpoint),
                         sentry=scfg is not None)
        # semantic-continuity bookkeeping: world-size gauge, and the batch
        # re-tune log line + breadcrumb when this segment starts at a
        # different world than the previous one (elastic shrink/grow)
        from tfde_tpu.resilience import elastic as elastic_lib

        _leaves = jax.tree_util.tree_leaves(first)
        _n = (int(_leaves[0].shape[0])
              if _leaves and getattr(_leaves[0], "shape", None) else 0)
        if shard_policy is AutoShardPolicy.OFF and jax.process_count() > 1:
            # under OFF every host yields the GLOBAL batch and the device
            # feed takes its slice — per-process is the quotient
            _n //= jax.process_count()
        elastic_lib.note_batch(_n, jax.process_count())
        # recompile sentinel on the train step: the batch shapes are pinned
        # by the pipeline, so past the first compile (and one legitimate
        # swap, e.g. an int8/ZeRO step change) every miss is a bug
        rc_site = recompile.site("train_step", stable=True)
        if memwatch.enabled():
            memwatch.install_collector()  # mem/live/* on the snapshot cadence
            if cfg.model_dir is not None:
                memwatch.arm(cfg.model_dir)

        def _step_fingerprint(b) -> tuple:
            return tuple(
                (tuple(getattr(leaf, "shape", ())),
                 str(getattr(leaf, "dtype", type(leaf).__name__)))
                for leaf in jax.tree_util.tree_leaves(b)
            )

        last_metrics = None
        compiled = False  # first step = trace+compile+execute, timed apart
        t_window = time.perf_counter()
        window_step = start_step  # steps/sec windows span actual steps run
        excluded = 0.0  # summary-sync/eval seconds carved out of the window
        step = start_step
        guard = _PreemptionGuard()
        with guard:
            for batch in feed:
                if step >= max_steps or guard.fired is not None:
                    break
                # step time is measured start-to-start: the whole iteration
                # minus the separately-categorized chunks (compile, device
                # sync, summary write, checkpoint, eval). Wrapping only the
                # dispatch call undercounts badly — under async dispatch the
                # device drains during host bookkeeping between statements,
                # and on a CPU mesh the compute threads starve the host
                # thread so the cost smears across the whole loop body.
                # Iteration coverage keeps the goodput ledger's intervals
                # disjoint (data waits happen between iterations, inside the
                # feed) and makes the breakdown sum to loop wall-clock.
                t_iter = time.perf_counter()
                iter_overhead = 0.0  # categorized seconds inside this iter
                if not compiled:
                    # the first call traces+compiles synchronously and the
                    # block drains its execution: the whole cost lands in
                    # compile_seconds, NOT in the train/step histogram or
                    # the first steps/sec window (both were poisoned by it
                    # before)
                    t0 = time.perf_counter()
                    pre_site_s = rc_site.seconds
                    with rc_site.watch(*_step_fingerprint(batch)):
                        if sstate is not None:
                            state, last_metrics, sstate = self._train_step(
                                state, batch, rng, sstate)
                        else:
                            state, last_metrics = self._train_step(
                                state, batch, rng)
                        jax.block_until_ready(last_metrics)
                    compile_s = time.perf_counter() - t0
                    iter_overhead += compile_s
                    compiled = True
                    metrics.counter("train/compile_seconds").incr(compile_s)
                    # the sentinel-measured portion of the first step, so
                    # goodput can diff later site compiles against what the
                    # first-step wall already covers
                    metrics.counter("train/compile_seconds_measured").incr(
                        max(0.0, rc_site.seconds - pre_site_s))
                    log.info("first step (compile): %.2fs", compile_s)
                    flightrec.record("compile", seconds=round(compile_s, 3),
                                     step=step + 1)
                    # interrogate the just-compiled program: the NEW
                    # state/carry have the same avals the executable
                    # was specialized on (the old buffers were donated)
                    sargs = ((state, batch, rng, sstate)
                             if sstate is not None
                             else (state, batch, rng))
                    if memwatch.enabled():
                        memwatch.register("train_step", self._train_step,
                                          args=sargs, donated=state)
                    # same seam feeds the lowered-program linter (no-op
                    # unless armed — tools/lintgate.py / TFDE_HLOLINT)
                    hlolint.offer("train_step", self._train_step,
                                  args=sargs, donated=state)
                    if writer is not None:
                        writer.scalars(step + 1,
                                       {"compile_seconds": compile_s})
                else:
                    with span("train/dispatch"), \
                            rc_site.watch(*_step_fingerprint(batch)):
                        if sstate is not None:
                            state, last_metrics, sstate = self._train_step(
                                state, batch, rng, sstate)
                        else:
                            state, last_metrics = self._train_step(
                                state, batch, rng)
                # keep the live reference fresh: the previous state's
                # buffers were donated to the step, so a stale self._state
                # would reference deleted arrays if train() is interrupted
                # mid-run
                self._state = state
                step += 1
                if step - start_step == 1:
                    # first-step wall excluded from the steps/sec window
                    t_window = time.perf_counter()
                    window_step = step
                profiler.step(step)
                if monitor is not None:
                    # polls the device flag every poll_every steps; raises
                    # NumericsError (action='raise') which unwinds through
                    # the guard to the supervisor as FailureKind.NUMERICS —
                    # before this step's summary/checkpoint below, so no
                    # post-NaN state is written
                    monitor.maybe_poll(sstate, step)
                if writer is not None and step % cfg.save_summary_steps == 0:
                    t_sync = time.perf_counter()
                    with span("train/device_sync"):
                        # blocks until the device queue drains — under
                        # async dispatch this is where compute time is paid
                        vals = {k: float(jax.device_get(v))
                                for k, v in last_metrics.items()}
                    with span("train/summary_write"):
                        writer.scalars(step, vals)
                        if mlog is not None:
                            mlog.write(step)
                        if ops_writer is not None:
                            exposition.export_to_tensorboard(ops_writer, step)
                    dt_sync = time.perf_counter() - t_sync
                    excluded += dt_sync
                    iter_overhead += dt_sync
                if step % cfg.log_step_count_steps == 0 and step > window_step:
                    # honest steady-state rate: the window covers exactly
                    # (step - window_step) steps and the summary/eval wall
                    # carved out above is attributed, not averaged in
                    dt = time.perf_counter() - t_window - excluded
                    n = step - window_step
                    sps = n / dt if dt > 0 else float("inf")
                    metrics.gauge("train/steps_per_sec").set(sps)
                    if writer is not None:
                        writer.scalars(step, {"global_step/sec": sps})
                    log.info("step %d: %.2f steps/sec", step, sps)
                    flightrec.record("step", step=step,
                                     steps_per_sec=round(sps, 3))
                    t_window = time.perf_counter()
                    window_step = step
                    excluded = 0.0
                if mngr is not None and step % cfg.save_checkpoints_steps == 0:
                    t_ck = time.perf_counter()
                    mngr.save(state)  # records its own checkpoint/save span
                    iter_overhead += time.perf_counter() - t_ck
                if _eval_hook is not None:
                    t_eval = time.perf_counter()
                    with span("train/eval"):
                        _eval_hook(state, step)
                    dt_eval = time.perf_counter() - t_eval
                    excluded += dt_eval
                    iter_overhead += dt_eval
                record("train/step",
                       max(0.0, time.perf_counter() - t_iter - iter_overhead))

            self._state = state
            profiler.close()
            profiler_lib.hub().unregister("train_step_window")
            flightrec.record(
                "train_end", step=step,
                preempted=(None if guard.fired is None else int(guard.fired)),
            )
            if mngr is not None:
                # also the preemption save: on a caught SIGTERM/SIGINT the
                # loop broke out and this force-save + wait commits the
                # current step before the signal is re-raised below
                mngr.save(state, force=True)
                mngr.wait()
            # goodput/* gauges reflect this train() call's wall-clock;
            # export before the final snapshot writes so they ride along
            rep = ledger.export()
            log.info(
                "goodput %.3f over %.1fs (%d steps; compile %.2fs, "
                "data-wait %.1f%%)",
                rep["goodput"], rep["wall_seconds"], rep["steps"],
                rep["seconds"]["compile"],
                100.0 * rep["fractions"]["data_wait"],
            )
            if mlog is not None:
                mlog.write(step)
                mlog.flush()
            if ops_writer is not None:
                exposition.export_to_tensorboard(ops_writer, step)
            if writer is not None:
                writer.flush()
        guard.reraise_if_fired(step if mngr is not None else None)
        return state

    # -- evaluate ------------------------------------------------------------
    def evaluate(
        self,
        input_fn: Callable[[], Iterable],
        steps: Optional[int] = None,
        name: str = "eval",
    ) -> dict:
        """Weighted full-dataset metrics (EvalSpec steps=None semantics)."""
        custom = self.loss_fn is not None or self.eval_fn is not None
        if custom and self.eval_fn is None:
            # decidable from configuration alone — fire before the batch
            # draw / init / checkpoint restore below, not after
            raise RuntimeError(
                "evaluate() on a custom-loss Estimator needs eval_fn: the "
                "training loss_fn takes an rng (dropout) and cannot promise "
                "a deterministic eval — pass eval_fn=(state, params, batch) "
                "-> {metric: batch mean}"
            )
        state = self._merged(self._state_for_inference(input_fn, "evaluate()"))
        strat = self.eval_strategy or self.strategy
        if self.eval_strategy is not None:
            # eval_distribute: re-lay the state out per the eval strategy
            # (the reference evaluates PS-trained variables under
            # MirroredStrategy, mnist_keras:241-243)
            from tfde_tpu.training.step import _state_shardings

            state = jax.device_put(state, _state_shardings(strat, state))
        if self._eval_step is None:
            if custom:
                self._eval_step = make_custom_eval_step(
                    strat, state, self.eval_fn
                )
            else:
                self._eval_step = make_eval_step(strat, state)
        totals = None
        n = 0
        if custom:
            # custom batches are arbitrary pytrees: no (images, labels)
            # padding protocol — feed them as produced (drop_remainder
            # batching upstream keeps shapes static). Validate leading-dim
            # divisibility per batch so a trailing partial batch fails with
            # the cause named instead of an opaque sharding error inside
            # device_put/jit.
            def _checked(it, divisor):
                for i, b in enumerate(it):
                    if divisor > 1:
                        for leaf in jax.tree_util.tree_leaves(b):
                            if not getattr(leaf, "ndim", 0):
                                continue  # scalars carry no batch dim
                            if leaf.shape[0] % divisor:
                                raise ValueError(
                                    f"evaluate[{name}]: batch {i} has a "
                                    f"leaf with leading dim "
                                    f"{leaf.shape[0]}, not divisible by "
                                    f"the strategy's batch divisor "
                                    f"{divisor}. The usual cause is a "
                                    f"trailing partial batch — batch the "
                                    f"eval input_fn with "
                                    f"drop_remainder=True, or pad it"
                                )
                    yield b

            feed = device_prefetch(
                _checked(iter(input_fn()), strat.batch_divisor), strat.mesh,
                wait_metric="eval/data_wait",
            )
        else:
            divisor = strat.batch_divisor
            padded = (pad_batch_for_mesh(b, divisor) for b in input_fn())
            feed = device_prefetch(padded, strat.mesh,
                                   wait_metric="eval/data_wait")
        for batch in feed:
            if steps is not None and n >= steps:
                break
            m = self._eval_step(state, batch)
            # accumulate on device; a single host fetch happens after the loop
            totals = m if totals is None else jax.tree_util.tree_map(jnp.add, totals, m)
            n += 1
        if totals is None:
            if custom:
                log.warning("evaluate[%s]: input_fn produced no batches", name)
                return {}
            return {"loss": float("nan"), "accuracy": float("nan")}
        totals = jax.device_get(totals)
        if custom:
            # user weights are arbitrary positive reals — divide by the true
            # sum (clamping would silently deflate fractional weights);
            # weight <= 0 means nothing was measured
            weight = float(totals["weight"])
            results = {
                k: (float(v) / weight if weight > 0 else float("nan"))
                for k, v in totals.items() if k != "weight"
            }
        else:
            weight = max(float(totals["weight"]), 1.0)
            results = {
                "loss": float(totals["loss_sum"]) / weight,
                "accuracy": float(totals["correct_sum"]) / weight,
            }
        step = int(jax.device_get(state.step))
        w = self._writer(name)
        if w is not None:
            w.scalars(step, results)
            w.flush()
        log.info("eval[%s] @ step %d: %s", name, step, results)
        return results

    def reload_from_checkpoint(
        self, input_fn, newer_than: Optional[int] = None
    ) -> Optional[int]:
        """Restore the *newest* checkpoint into this estimator, re-reading
        the directory every call (unlike the resume-by-default path, which
        restores once) — the continuous-eval flow. Returns the restored
        global step; None if the directory has no checkpoint yet or none
        newer than `newer_than` (the cheap no-restore path a polling
        evaluator takes on idle ticks)."""
        mngr = self._ckpt_mngr()
        if mngr is None:
            return None
        mngr.reload()  # another process/thread writes this directory
        latest = mngr.latest_step
        if latest is None or (newer_than is not None and latest <= newer_than):
            return None
        first = next(iter(input_fn()))
        state = self._ensure_state(first)
        restored = mngr.restore_latest(state)
        if restored is None:
            return None
        self._state = restored
        self._from_checkpoint = True
        return int(jax.device_get(restored.step))

    # -- predict -------------------------------------------------------------
    def predict(self, input_fn: Callable[[], Iterable]):
        """Yield per-batch softmax probabilities (serving signature §3.4)."""
        state = self._merged(self._state_for_inference(input_fn, "predict()"))

        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats

        if self._predict_fn is None:
            apply_fn = state.apply_fn

            @jax.jit
            def infer(variables, x):
                return jax.nn.softmax(apply_fn(variables, x, train=False), axis=-1)

            self._predict_fn = infer  # compiled once; variables passed per call

        for batch in input_fn():
            x = batch[0] if isinstance(batch, tuple) else batch
            yield np.asarray(jax.device_get(self._predict_fn(variables, jnp.asarray(x))))

    # -- export --------------------------------------------------------------
    def export_saved_model(self, exporter, metrics=None) -> Optional[str]:
        """Run an exporter against the current (or checkpointed) state
        (chief only). A metric-gated exporter (BestExporter — anything
        with `maybe_export`) receives `metrics` and decides for itself;
        without metrics (empty eval, no eval yet) it SKIPS with a
        warning — a gated export of a never-evaluated model would violate
        its contract."""
        if self._state is None:
            shape = [1 if d is None else d for d in exporter.input_shape]
            sample = np.zeros(shape, np.dtype(exporter.input_dtype))
            state = self._state_for_inference(lambda: [(sample,)], "export")
        else:
            state = self._state
        if not self._is_chief or self.config.model_dir is None:
            return None
        state = self._merged(state)
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats

        def apply_fn(variables, x):
            return self.model.apply(variables, x, train=False)

        if hasattr(exporter, "maybe_export"):
            if not metrics:
                # a gated exporter without metrics must SKIP — exporting a
                # never-evaluated model would violate its contract
                log.warning(
                    "skipping metric-gated exporter %r: no eval metrics "
                    "available", exporter.name,
                )
                return None
            return exporter.maybe_export(
                self.config.model_dir, apply_fn, variables, metrics
            )
        return exporter.export(self.config.model_dir, apply_fn, variables)

    def close(self) -> None:
        if self._ckpt is not None:
            self._ckpt.wait()
            self._ckpt.close()
        for w in self._writers.values():
            w.close()
        if self._metrics_log is not None:
            self._metrics_log.close()
            self._metrics_log = None
        if self._pusher is not None:
            self._pusher.close()  # final push: chief sees the end state
            self._pusher = None
        if self._metrics_srv is not None:
            self._metrics_srv.close()
            self._metrics_srv = None
            self._aggregator = None


def continuous_eval(
    estimator: Estimator,
    eval_spec: EvalSpec,
    stop_after_step: Optional[int] = None,
    poll_secs: Optional[float] = None,
    idle_timeout_secs: Optional[float] = None,
    stop_event=None,
) -> Tuple[int, dict]:
    """Evaluator-job loop: evaluate each NEW checkpoint in model_dir as it
    appears — the reference's *separate-cluster* evaluator capability
    (`train_and_evaluate` runs eval in its own process group concurrently
    with training, mnist_keras_distributed.py:255-283). Run this from a
    dedicated process (group) sharing the trainer's model_dir — the
    TF_CONFIG 'evaluator' role analog — or let
    `train_and_evaluate(eval_mode="from_checkpoint")` drive it in a thread.

    Stops when `stop_after_step` is reached, `idle_timeout_secs` passes with
    no new checkpoint, or `stop_event` is set (after a final catch-up pass).
    Returns (last_evaluated_step, last_metrics).

    Metric-gated exporters in `eval_spec.exporters` (BestExporter) run
    after EVERY evaluated checkpoint — the per-eval gating the
    tf.estimator contract describes; plain exporters stay end-of-training
    (the caller's final-export loop).
    """
    poll = eval_spec.throttle_secs if poll_secs is None else poll_secs
    seen, last = -1, {}
    idle_since = time.time()

    def eval_new() -> bool:
        nonlocal seen, last, idle_since
        step = estimator.reload_from_checkpoint(
            eval_spec.input_fn, newer_than=None if seen < 0 else seen
        )
        if step is None or step <= seen:
            return False
        seen = step
        idle_since = time.time()
        last = estimator.evaluate(eval_spec.input_fn, eval_spec.steps, eval_spec.name)
        for exporter in eval_spec.exporters:
            if hasattr(exporter, "maybe_export"):
                estimator.export_saved_model(exporter, metrics=last)
        return True

    while True:
        eval_new()
        if stop_after_step is not None and seen >= stop_after_step:
            break
        if stop_event is not None and stop_event.is_set():
            # a checkpoint may have landed while we were evaluating: one
            # final catch-up so the trainer's force-saved last step is seen
            eval_new()
            break
        if (idle_timeout_secs is not None
                and time.time() - idle_since > idle_timeout_secs):
            break
        if stop_event is not None:
            stop_event.wait(poll)
        else:
            time.sleep(poll)
    return seen, last


def train_and_evaluate(
    estimator: Estimator,
    train_spec: TrainSpec,
    eval_spec: EvalSpec,
    eval_mode: str = "inline",
) -> Tuple[TrainState, dict]:
    """The reference's lifecycle loop (mnist_keras:283), explicit:

    - train to max_steps, evaluating at most every throttle_secs once
      start_delay_secs have passed (EvalSpec, mnist_keras:274-275);
    - a final eval after training completes;
    - then run every exporter (FinalExporter semantics, §3.4).
    Returns (final_state, final_eval_metrics).

    eval_mode:
    - "inline" (default): eval runs on the training mesh between steps;
      training pauses for its duration (documented deviation — no idle eval
      fleet on TPU).
    - "from_checkpoint": eval runs concurrently in a background thread (on
      the chief) against the latest checkpoint via `continuous_eval`, so the
      train-step cadence is unaffected — the reference's concurrent-
      evaluator behavior in one process. Requires model_dir + checkpointing;
      single-process only (a multi-process evaluator is a dedicated job
      running `continuous_eval`, like the reference's evaluator cluster).
    """
    if estimator.loss_fn is not None and estimator.eval_fn is None:
        # evaluate() would raise this hours in, after the training budget
        # is spent — the promise of an eval makes the check an entry check
        raise RuntimeError(
            "train_and_evaluate on a custom-loss Estimator needs eval_fn "
            "(the rng-taking loss_fn cannot promise a deterministic eval)"
        )
    if eval_mode not in ("inline", "from_checkpoint"):
        raise ValueError(f"unknown eval_mode {eval_mode!r}")
    if eval_mode == "from_checkpoint":
        return _train_with_continuous_eval(estimator, train_spec, eval_spec)

    t_start = time.time()
    last_eval = {"t": t_start}

    def eval_hook(state, step):
        now = time.time()
        if now - t_start < eval_spec.start_delay_secs:
            return
        if now - last_eval["t"] < eval_spec.throttle_secs:
            return
        last_eval["t"] = now
        m = estimator.evaluate(eval_spec.input_fn, eval_spec.steps,
                               eval_spec.name)
        # metric-gated exporters run after EVERY throttled eval (the
        # tf.estimator contract: BestExporter compares per eval); plain
        # FinalExporters wait for the end
        for exporter in eval_spec.exporters:
            if hasattr(exporter, "maybe_export"):
                estimator.export_saved_model(exporter, metrics=m)

    state = estimator.train(
        train_spec.input_fn,
        train_spec.max_steps,
        shard_policy=train_spec.shard_policy,
        _eval_hook=eval_hook,
    )
    metrics = estimator.evaluate(eval_spec.input_fn, eval_spec.steps, eval_spec.name)
    for exporter in eval_spec.exporters:
        estimator.export_saved_model(exporter, metrics=metrics)
    return state, metrics


def _train_with_continuous_eval(
    estimator: Estimator, train_spec: TrainSpec, eval_spec: EvalSpec
) -> Tuple[TrainState, dict]:
    import threading

    cfg = estimator.config
    if cfg.model_dir is None or not cfg.save_checkpoints_steps:
        raise ValueError(
            "eval_mode='from_checkpoint' needs model_dir + "
            "save_checkpoints_steps: eval reads what the trainer checkpoints"
        )
    if jax.process_count() > 1:
        raise ValueError(
            "eval_mode='from_checkpoint' inside the trainer is single-process "
            "(a background thread cannot coordinate multi-process collectives); "
            "run continuous_eval() as a dedicated evaluator job instead"
        )

    # A separate Estimator instance = the 'evaluator job': own eval-step
    # compilation (on eval_strategy if given), own checkpoint reader.
    evaluator = Estimator(
        estimator.model,
        estimator.tx,
        strategy=estimator.eval_strategy or estimator.strategy,
        config=cfg,
        loss_fn=estimator.loss_fn,
        eval_fn=estimator.eval_fn,
        # LoRA: the trainer checkpoints adapters-only state — the evaluator
        # must build the same adapter template to restore it, and merge
        # before evaluating
        lora=estimator.lora,
        lora_base_params=estimator._lora_base,
    )
    stop = threading.Event()
    box: dict = {}

    def loop():
        try:
            stop.wait(eval_spec.start_delay_secs)
            box["result"] = continuous_eval(evaluator, eval_spec,
                                            stop_event=stop)
        except BaseException as e:  # surfaced to the caller after train
            box["error"] = e

    thread = threading.Thread(target=loop, daemon=True, name="continuous-eval")
    thread.start()
    try:
        state = estimator.train(
            train_spec.input_fn,
            train_spec.max_steps,
            shard_policy=train_spec.shard_policy,
        )
    finally:
        stop.set()
    thread.join(timeout=600.0)
    if thread.is_alive():
        # don't tear down resources under a still-running eval; leak instead
        log.error("continuous-eval thread did not finish within 600s; "
                  "skipping evaluator teardown")
    else:
        evaluator.close()
    if "error" in box:
        raise RuntimeError(
            "continuous evaluator failed during training"
        ) from box["error"]
    _, metrics = box.get("result", (-1, {}))
    for exporter in eval_spec.exporters:
        # gated exporters already ran per evaluated checkpoint inside
        # continuous_eval; this pass is the safety net (evaluator thread
        # produced no evals -> skip with a warning) and the plain
        # FinalExporters' end-of-training run. Re-gating with the final
        # metrics is a guaranteed no-op (strict-improvement bar).
        estimator.export_saved_model(exporter, metrics=metrics)
    return state, metrics
