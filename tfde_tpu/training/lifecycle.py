"""Estimator-style training lifecycle: train_and_evaluate with eval
throttling, periodic checkpoint/summaries, resume-by-default, final export.

Re-specifies explicitly the implicit `tf.estimator.train_and_evaluate`
behavior the reference relies on (SURVEY.md §7 "Estimator-lifecycle
fidelity"): TrainSpec.max_steps bounds training (mnist_keras:255-262);
EvalSpec runs the *full* eval set when steps=None, no earlier than
start_delay_secs after start and at most every throttle_secs (mnist_keras:
264-275); checkpoints every RunConfig.save_checkpoints_steps into model_dir
with transparent resume on restart (mnist_keras:245-248); scalar summaries
every save_summary_steps and steps/sec every log_step_count_steps
(mnist_keras:246-247); FinalExporter artifacts written at end of training
(mnist_keras:264; §3.4).

Differences from the reference, on purpose:
- train and eval interleave in one SPMD process group (every chip trains;
  eval is a compiled pass on the same mesh) instead of a separate eval
  cluster — there is no idle eval fleet on TPU.
- checkpoint saves are async (Orbax): the train loop never blocks on I/O.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.data.device import device_prefetch
from tfde_tpu.data.pipeline import AutoShardPolicy
from tfde_tpu.observability.profiler import StepWindowProfiler
from tfde_tpu.observability.tensorboard import SummaryWriter
from tfde_tpu.parallel.strategies import Strategy, MultiWorkerMirroredStrategy
from tfde_tpu.training.step import (
    init_state,
    make_train_step,
    make_eval_step,
    pad_batch_for_mesh,
)
from tfde_tpu.training.train_state import TrainState

log = logging.getLogger(__name__)


@dataclasses.dataclass
class RunConfig:
    """Training-run configuration (tf.estimator.RunConfig analog,
    mnist_keras:240-248)."""

    model_dir: Optional[str] = None
    save_summary_steps: int = 100
    log_step_count_steps: int = 100
    # None/0 disables checkpointing (and resume) entirely — useful when the
    # model_dir is a filesystem the checkpoint backend doesn't support
    # (Orbax/tensorstore speak gs:// but not e.g. memory://), or for
    # throwaway runs. Summaries and export still honor model_dir.
    save_checkpoints_steps: Optional[int] = 500
    keep_checkpoint_max: int = 5
    # (start, stop) global-step window to capture a profiler trace into
    # <model_dir>/plugins/profile — the reference's ProfilerHook capability
    # (mnist_keras:235-237,261). None defers to $TFDE_PROFILE ("start:stop").
    profile_steps: Optional[Tuple[int, int]] = None
    seed: int = 0


@dataclasses.dataclass
class TrainSpec:
    """input_fn -> Dataset/iterable of (images, labels) host batches."""

    input_fn: Callable[[], Iterable]
    max_steps: int
    shard_policy: AutoShardPolicy = AutoShardPolicy.DATA


@dataclasses.dataclass
class EvalSpec:
    input_fn: Callable[[], Iterable]
    steps: Optional[int] = None  # None = full pass (mnist_keras:271)
    name: str = "eval"
    exporters: Sequence = ()
    start_delay_secs: float = 10.0
    throttle_secs: float = 10.0


class Estimator:
    """Owns model + optimizer + strategy + run config; train/evaluate/predict/
    export with checkpoint-resume (the tf.keras.estimator.model_to_estimator
    capability, mnist_keras:118-119, minus the Keras conversion detour)."""

    def __init__(
        self,
        model,
        optimizer,
        strategy: Optional[Strategy] = None,
        config: Optional[RunConfig] = None,
    ):
        self.model = model
        self.tx = optimizer
        self.strategy = strategy or MultiWorkerMirroredStrategy()
        self.config = config or RunConfig()
        self._state: Optional[TrainState] = None
        self._ckpt: Optional[CheckpointManager] = None
        self._train_step = None
        self._eval_step = None
        self._predict_fn = None
        self._writers: dict[str, SummaryWriter] = {}

    # -- internals -----------------------------------------------------------
    @property
    def _is_chief(self) -> bool:
        return jax.process_index() == 0

    def _writer(self, name: str = "") -> Optional[SummaryWriter]:
        if self.config.model_dir is None or not self._is_chief:
            return None
        if name not in self._writers:
            logdir = self.config.model_dir
            if name:
                logdir = f"{logdir}/{name}"
            self._writers[name] = SummaryWriter(logdir)
        return self._writers[name]

    def _ckpt_mngr(self) -> Optional[CheckpointManager]:
        if self.config.model_dir is None or not self.config.save_checkpoints_steps:
            return None
        if self._ckpt is None:
            self._ckpt = CheckpointManager(
                f"{self.config.model_dir}/checkpoints",
                max_to_keep=self.config.keep_checkpoint_max,
            )
        return self._ckpt

    def _ensure_state(self, sample_batch) -> TrainState:
        if self._state is None:
            sample = jnp.zeros(
                np.asarray(sample_batch[0]).shape, np.asarray(sample_batch[0]).dtype
            )
            self._state, _ = init_state(
                self.model, self.tx, self.strategy, sample, seed=self.config.seed
            )
            self._from_checkpoint = False
            mngr = self._ckpt_mngr()
            if mngr is not None:
                restored = mngr.restore_latest(self._state)
                if restored is not None:
                    self._state = restored  # resume-by-default (SURVEY.md §5)
                    self._from_checkpoint = True
        return self._state

    def _state_for_inference(self, input_fn, what: str) -> TrainState:
        """State for evaluate/predict/export: live if this process trained,
        else restored from model_dir (the Estimator eval-from-checkpoint
        flow); error only when neither exists."""
        if self._state is not None:
            return self._state
        first = next(iter(input_fn()))
        state = self._ensure_state(first)
        if not self._from_checkpoint:
            self._state = None  # don't let later train() skip resume logic
            raise RuntimeError(
                f"{what} before train(): no trained state in this process and "
                f"no checkpoint found in model_dir={self.config.model_dir!r}"
            )
        return state

    # -- train ---------------------------------------------------------------
    def train(
        self,
        input_fn: Callable[[], Iterable],
        max_steps: int,
        shard_policy: AutoShardPolicy = AutoShardPolicy.DATA,
        _eval_hook: Optional[Callable[[TrainState, int], None]] = None,
    ) -> TrainState:
        """Train until global step reaches max_steps (TrainSpec semantics:
        max_steps is absolute, so a resumed run does only the remainder —
        matching Estimator's behavior with mnist_keras:262)."""
        cfg = self.config
        host_iter = iter(input_fn())
        first = next(host_iter)
        state = self._ensure_state(first)
        start_step = int(jax.device_get(state.step))
        if start_step >= max_steps:
            log.info("global step %d >= max_steps %d; nothing to do", start_step, max_steps)
            return state
        if self._train_step is None:
            self._train_step = make_train_step(self.strategy, state)

        rng = jax.random.key(cfg.seed + 1)
        writer = self._writer()
        mngr = self._ckpt_mngr()
        profiler = (
            StepWindowProfiler(cfg.model_dir, cfg.profile_steps)
            if self._is_chief
            else StepWindowProfiler(None, None)
        )

        def batches():
            yield first
            yield from host_iter

        feed = device_prefetch(batches(), self.strategy.mesh, policy=shard_policy)
        last_metrics = None
        t_window = time.time()
        step = start_step
        for batch in feed:
            if step >= max_steps:
                break
            state, last_metrics = self._train_step(state, batch, rng)
            # keep the live reference fresh: the previous state's buffers were
            # donated to the step, so a stale self._state would reference
            # deleted arrays if train() is interrupted mid-run
            self._state = state
            step += 1
            profiler.step(step)
            if writer is not None and step % cfg.save_summary_steps == 0:
                vals = {k: float(jax.device_get(v)) for k, v in last_metrics.items()}
                writer.scalars(step, vals)
            if step % cfg.log_step_count_steps == 0:
                dt = time.time() - t_window
                sps = cfg.log_step_count_steps / dt if dt > 0 else float("inf")
                if writer is not None:
                    writer.scalars(step, {"global_step/sec": sps})
                log.info("step %d: %.2f steps/sec", step, sps)
                t_window = time.time()
            if mngr is not None and step % cfg.save_checkpoints_steps == 0:
                mngr.save(state)
            if _eval_hook is not None:
                _eval_hook(state, step)

        self._state = state
        profiler.close()
        if mngr is not None:
            mngr.save(state, force=True)
            mngr.wait()
        if writer is not None:
            writer.flush()
        return state

    # -- evaluate ------------------------------------------------------------
    def evaluate(
        self,
        input_fn: Callable[[], Iterable],
        steps: Optional[int] = None,
        name: str = "eval",
    ) -> dict:
        """Weighted full-dataset metrics (EvalSpec steps=None semantics)."""
        state = self._state_for_inference(input_fn, "evaluate()")
        if self._eval_step is None:
            self._eval_step = make_eval_step(self.strategy, state)
        totals = None
        n = 0
        divisor = self.strategy.batch_divisor
        padded = (pad_batch_for_mesh(b, divisor) for b in input_fn())
        feed = device_prefetch(padded, self.strategy.mesh)
        for batch in feed:
            if steps is not None and n >= steps:
                break
            m = self._eval_step(state, batch)
            # accumulate on device; a single host fetch happens after the loop
            totals = m if totals is None else jax.tree_util.tree_map(jnp.add, totals, m)
            n += 1
        if totals is None:
            return {"loss": float("nan"), "accuracy": float("nan")}
        totals = jax.device_get(totals)
        weight = max(float(totals["weight"]), 1.0)
        results = {
            "loss": float(totals["loss_sum"]) / weight,
            "accuracy": float(totals["correct_sum"]) / weight,
        }
        step = int(jax.device_get(state.step))
        w = self._writer(name)
        if w is not None:
            w.scalars(step, results)
            w.flush()
        log.info("eval[%s] @ step %d: %s", name, step, results)
        return results

    # -- predict -------------------------------------------------------------
    def predict(self, input_fn: Callable[[], Iterable]):
        """Yield per-batch softmax probabilities (serving signature §3.4)."""
        state = self._state_for_inference(input_fn, "predict()")

        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats

        if self._predict_fn is None:
            apply_fn = state.apply_fn

            @jax.jit
            def infer(variables, x):
                return jax.nn.softmax(apply_fn(variables, x, train=False), axis=-1)

            self._predict_fn = infer  # compiled once; variables passed per call

        for batch in input_fn():
            x = batch[0] if isinstance(batch, tuple) else batch
            yield np.asarray(jax.device_get(self._predict_fn(variables, jnp.asarray(x))))

    # -- export --------------------------------------------------------------
    def export_saved_model(self, exporter) -> Optional[str]:
        """Run a FinalExporter against the current (or checkpointed) state
        (chief only)."""
        if self._state is None:
            shape = [1 if d is None else d for d in exporter.input_shape]
            sample = np.zeros(shape, np.dtype(exporter.input_dtype))
            state = self._state_for_inference(lambda: [(sample,)], "export")
        else:
            state = self._state
        if not self._is_chief or self.config.model_dir is None:
            return None
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats

        def apply_fn(variables, x):
            return self.model.apply(variables, x, train=False)

        return exporter.export(self.config.model_dir, apply_fn, variables)

    def close(self) -> None:
        if self._ckpt is not None:
            self._ckpt.wait()
            self._ckpt.close()
        for w in self._writers.values():
            w.close()


def train_and_evaluate(
    estimator: Estimator, train_spec: TrainSpec, eval_spec: EvalSpec
) -> Tuple[TrainState, dict]:
    """The reference's lifecycle loop (mnist_keras:283), explicit:

    - train to max_steps, evaluating at most every throttle_secs once
      start_delay_secs have passed (EvalSpec, mnist_keras:274-275);
    - a final eval after training completes;
    - then run every exporter (FinalExporter semantics, §3.4).
    Returns (final_state, final_eval_metrics).
    """
    t_start = time.time()
    last_eval = {"t": t_start}

    def eval_hook(state, step):
        now = time.time()
        if now - t_start < eval_spec.start_delay_secs:
            return
        if now - last_eval["t"] < eval_spec.throttle_secs:
            return
        last_eval["t"] = now
        estimator.evaluate(eval_spec.input_fn, eval_spec.steps, eval_spec.name)

    state = estimator.train(
        train_spec.input_fn,
        train_spec.max_steps,
        shard_policy=train_spec.shard_policy,
        _eval_hook=eval_hook,
    )
    metrics = estimator.evaluate(eval_spec.input_fn, eval_spec.steps, eval_spec.name)
    for exporter in eval_spec.exporters:
        estimator.export_saved_model(exporter)
    return state, metrics
