"""Knowledge distillation — train a small student against a teacher's
soft targets.

The training-side companion of speculative decoding (inference/
speculative.py): a draft model is only as fast as its acceptance rate,
and acceptance is exactly agreement with the target's distribution — the
thing distillation optimizes. The same loss serves classic model
compression.

`make_distill_loss` returns a loss_fn for the existing custom-objective
machinery (training/step.py make_custom_train_step, or
Estimator(loss_fn=...)), so distillation inherits every strategy (DP/
FSDP/TP/...), grad accumulation, and the full lifecycle for free. The
teacher runs frozen inside the student's step — one fused program, no
separate teacher pipeline.

Teacher memory: the captured `teacher_params` become constants of the
compiled step and KEEP whatever sharding they carry — `jax.device_put`
them onto the layout you want (e.g. FSDP-shard a large teacher) BEFORE
calling; jit preserves a captured array's sharding. Host numpy teacher
params would be embedded replicated on every device — the loss_fn warns
and device_puts are the caller's lever.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

log = logging.getLogger(__name__)


def make_distill_loss(
    teacher_model,
    teacher_params,
    temperature: float = 2.0,
    hard_weight: float = 0.0,
):
    """loss_fn for make_custom_train_step: KL(teacher_T || student_T).

    batch is `(tokens,)` [B, S] int32 (the causal-LM convention,
    models/gpt.next_token_loss): both models score every position; the
    student matches the teacher's tempered distribution at each. The
    standard T^2 factor keeps gradient scale comparable across
    temperatures. `hard_weight` mixes in the data CE against the actual
    next tokens (0 = pure distillation).

    Metrics: `kl` (the objective term), `agreement` (argmax match rate
    with the teacher — the quantity speculative acceptance depends on),
    and `hard_loss` when hard_weight > 0.
    """
    if temperature <= 0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    if not 0.0 <= hard_weight <= 1.0:
        # outside [0, 1] the mix silently flips a term's sign — the KL
        # would become a reward for diverging from the teacher
        raise ValueError(f"hard_weight must be in [0, 1], got {hard_weight}")
    if any(
        not isinstance(leaf, jax.Array)
        for leaf in jax.tree_util.tree_leaves(teacher_params)
    ):
        log.warning(
            "teacher_params contain host arrays: they will be embedded "
            "REPLICATED in the compiled step — jax.device_put them with "
            "the sharding you want (see module docstring)"
        )

    def loss_fn(state, params, batch, rng):
        (tokens,) = batch if isinstance(batch, tuple) else (batch,)
        student_logits = state.apply_fn(
            {"params": params}, tokens, train=True, rngs={"dropout": rng}
        )
        teacher_logits = jax.lax.stop_gradient(
            teacher_model.apply({"params": teacher_params}, tokens,
                                train=False)
        )
        # align: predictions for positions 1..S-1
        s = student_logits[:, :-1].astype(jnp.float32)
        t = teacher_logits[:, :-1].astype(jnp.float32)
        t_logp = jax.nn.log_softmax(t / temperature, axis=-1)
        s_logp = jax.nn.log_softmax(s / temperature, axis=-1)
        kl = jnp.mean(
            jnp.sum(jnp.exp(t_logp) * (t_logp - s_logp), axis=-1)
        ) * temperature ** 2
        agreement = jnp.mean(
            (jnp.argmax(s, axis=-1) == jnp.argmax(t, axis=-1)).astype(
                jnp.float32
            )
        )
        loss = kl
        metrics = {"kl": kl, "agreement": agreement}
        if hard_weight > 0.0:
            from tfde_tpu.ops.losses import masked_lm_loss

            hard, _ = masked_lm_loss(
                student_logits[:, :-1], tokens[:, 1:].astype(jnp.int32)
            )
            loss = (1.0 - hard_weight) * kl + hard_weight * hard
            metrics["hard_loss"] = hard
        return loss, metrics

    return loss_fn
