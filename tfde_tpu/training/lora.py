"""LoRA — low-rank adapter fine-tuning with the base model frozen.

Parameter-efficient fine-tuning for the converted-checkpoint workflow
(models/convert.py brings a pretrained GPT-2/BERT/LLaMA in; this trains
it on a downstream objective while touching ~1% of the parameters).
Beyond-reference scale-up scope, like distillation (training/distill.py):
the reference trains every variable every step (its optimizer applies to
the full var list, /root/reference/tf2_mnist_distributed.py:85-90); at
converted-LLM size that is neither necessary nor cheap, and LoRA is the
standard alternative.

Design — adapters ARE the TrainState, the base is a frozen closure:

- `init_lora(params, config, rng)` builds a tiny tree of `{a, b}` pairs
  mirroring the targeted kernels. `b` starts at zero, so the adapted
  model is EXACTLY the base model at step 0.
- `merge_lora(base, lora, config)` returns base-shaped params with
  `W + (alpha/rank) * a @ b` folded in. It runs *inside* the compiled
  step (XLA fuses the rank-r outer product into the surrounding graph),
  and again at export time to produce a plain checkpoint any consumer of
  the base architecture can load (`merge_lora` output feeds
  export/serving.py unchanged).
- `make_lora_loss(base_params, loss_fn, config)` adapts any existing
  loss (classification, MLM, distillation, ...) to take the adapter tree
  as its `params`. The result drives the untouched custom-objective
  machinery (training/step.py make_custom_train_step, or
  Estimator(loss_fn=...)), so LoRA inherits every strategy, grad
  accumulation, checkpointing, and the lifecycle for free — the
  optimizer state (AdamW mu/nu) is rank-r too, which is the actual
  memory win.

Base-params memory: the captured `base_params` become constants of the
compiled step and KEEP whatever sharding they carry (same contract as
the distillation teacher, training/distill.py) — `jax.device_put` them
onto the layout you want before calling.

Targeting: `config.target` is a regex tested against the '/'-joined
param path; the default hits every `kernel` leaf of rank >= 2. Kernels
are factorized as the matrix of their actual contraction: the attention
stack's DenseGeneral layouts (transformer.py — `query`/`key`/`value`/
fused `qkv` contract axis 0 into multi-head features; `out` contracts
the leading (heads, head_dim) axes) split accordingly, everything else
(Dense 2-D, conv [h, w, cin, cout]) splits as [prod(leading), last] —
in every case `a @ b` is rank-r with respect to the true input->output
map, the standard LoRA semantics. Restrict HF-style with e.g.
`target=r"attn/(query|value)/kernel$"`.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import traverse_util


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """rank: adapter rank r. alpha: scale numerator (delta is scaled by
    alpha/rank, so tuning rank does not retune the LR). target: regex over
    the '/'-joined param path; default hits every 2-D `kernel`."""

    rank: int = 8
    alpha: float = 16.0
    target: str = r"kernel$"

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


# Modules whose kernel contracts over axis 0 into multi-axis features
# (flax DenseGeneral with tuple `features`): the attention projections of
# models/transformer.py. Everything else contracts [prod(leading), last].
_AXIS0_CONTRACTION = frozenset({"query", "key", "value", "qkv"})


def _matrix_shape(path, w) -> tuple:
    """(in_features, out_features) of the kernel's true contraction map."""
    if w.ndim == 2:
        return w.shape[0], w.shape[1]
    if len(path) >= 2 and path[-2] in _AXIS0_CONTRACTION:
        return w.shape[0], int(np.prod(w.shape[1:]))
    return int(np.prod(w.shape[:-1])), w.shape[-1]


def lora_target_paths(params: Any, config: LoraConfig) -> list:
    """The param paths (tuples of names) the config adapts: rank >= 2
    leaves whose '/'-joined path matches `config.target`."""
    pat = re.compile(config.target)
    flat = traverse_util.flatten_dict(params)
    return [
        path
        for path, w in sorted(flat.items())
        if getattr(w, "ndim", 0) >= 2 and pat.search("/".join(path))
    ]


def init_lora(params: Any, config: LoraConfig, rng: jax.Array) -> Any:
    """Build the adapter tree: for each targeted kernel [in, out], a pair
    `a` [in, r] ~ N(0, 1/sqrt(in)) and `b` [r, out] = 0 (standard LoRA
    init: the delta starts at exactly zero). Adapters take the kernel's
    dtype. Raises if the target regex matches nothing — a silent no-op
    fine-tune is never what the caller meant."""
    paths = lora_target_paths(params, config)
    if not paths:
        raise ValueError(
            f"LoRA target regex {config.target!r} matches no rank>=2 kernel "
            f"in the param tree — check the path names "
            f"(e.g. {['/'.join(p) for p in list(traverse_util.flatten_dict(params))[:3]]})"
        )
    flat = traverse_util.flatten_dict(params)
    out = {}
    for i, path in enumerate(paths):
        w = flat[path]
        d_in, d_out = _matrix_shape(path, w)
        key = jax.random.fold_in(rng, i)
        a = (
            jax.random.normal(key, (d_in, config.rank), jnp.float32)
            / jnp.sqrt(d_in)
        ).astype(w.dtype)
        out[path + ("a",)] = a
        out[path + ("b",)] = jnp.zeros((config.rank, d_out), w.dtype)
    return traverse_util.unflatten_dict(out)


def merge_lora(base_params: Any, lora_params: Any, config: LoraConfig) -> Any:
    """base-shaped params with each adapted kernel replaced by
    W + (alpha/rank) * a @ b. The a@b product runs in fp32 and casts back
    to W's dtype (rank-r GEMMs are tiny; bf16 accumulation there would be
    pure noise). Used both inside the compiled step and at export time."""
    flat = dict(traverse_util.flatten_dict(base_params))
    flat_lora = traverse_util.flatten_dict(lora_params)
    pairs = {}
    for path, leaf in flat_lora.items():
        pairs.setdefault(path[:-1], {})[path[-1]] = leaf
    for path, ab in pairs.items():
        if path not in flat:
            raise ValueError(
                f"LoRA adapter at {'/'.join(path)} has no matching base kernel"
            )
        w = flat[path]
        delta = (
            ab["a"].astype(jnp.float32) @ ab["b"].astype(jnp.float32)
        ) * config.scale
        flat[path] = (
            w.astype(jnp.float32) + delta.reshape(w.shape)
        ).astype(w.dtype)
    return traverse_util.unflatten_dict(flat)


def lora_param_count(lora_params: Any) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(lora_params))


def make_lora_loss(
    base_params: Any,
    loss_fn: Callable,
    config: LoraConfig,
) -> Callable:
    """Adapt `loss_fn(state, params, batch, rng)` so `params` is the adapter
    tree: merges into the frozen base, then delegates. Feed the result to
    make_custom_train_step / Estimator(loss_fn=...) with a TrainState whose
    `params` are `init_lora(...)` output — gradients (and optimizer slots)
    exist only for the adapters."""

    def lora_loss(state, lora_params, batch, rng):
        merged = merge_lora(base_params, lora_params, config)
        return loss_fn(state, merged, batch, rng)

    return lora_loss


def init_lora_state(
    model,
    tx,
    strategy,
    base_params: Any,
    config: LoraConfig,
    seed: int = 0,
    batch_stats: Any = None,
):
    """A TrainState whose `params` (and optimizer state) are the rank-r
    adapters, sharded per the strategy (adapters replicate under every DP
    strategy — they are small by construction). Returns (state, shardings);
    drive it with `make_custom_train_step(strategy, state,
    make_lora_loss(base_params, your_loss, config))`."""
    from tfde_tpu.training.step import _state_shardings
    from tfde_tpu.training.train_state import TrainState

    def init_fn(rng):
        lora = init_lora(base_params, config, rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=lora,
            batch_stats=batch_stats or {},
            opt_state=tx.init(lora),
            apply_fn=model.apply,
            tx=tx,
        )

    abstract = jax.eval_shape(init_fn, jax.random.key(seed))
    shardings = _state_shardings(strategy, abstract)
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.key(seed))
    return state, shardings
