"""Prefix-KV cache: shared prompt prefixes prefill once per replica.

Serving traffic is dominated by shared prefixes — a system prompt, a
few-shot preamble, a conversation re-sent turn by turn. The continuous
batcher (inference/server.py) pays a full prefill per admission anyway,
because each row's K/V is recomputed from token ids. This module keeps
the K/V itself: a token TRIE over BLOCK-sized prompt chunks, each node
holding the device-resident K/V segment for its block. On admission the
batcher walks the trie for the longest cached prefix, scatters those
segments into the fresh row cache, and prefills only the uncached
suffix (`_prefill_suffix` in server.py) — so an N-request wave sharing
a 512-token system prompt prefills those 512 tokens once, ever.

Design points:

- BLOCK granularity (vLLM-style, default 16 tokens): a prefix is usable
  only in whole blocks, so the trie keys are hashable token tuples and
  the warm-admission program compiles O(max_len / block) variants of the
  prefix length L, not one per token count.
- Segments are stored per (leaf, block) as device arrays shaped
  [block, ...] — exactly the row slice `leaf[row, b*block:(b+1)*block]`
  of a prefill's output cache, so a warm row is bit-identical to a cold
  one (tests/test_prefix_cache.py pins greedy parity cache-on vs -off).
- LRU byte budget: eviction removes least-recently-used LEAF nodes only
  (childless — interior nodes stay while any extension is resident, so
  every stored path remains walkable from the root). Nodes touched by
  the in-progress lookup/insert are protected, so an insert can never
  evict its own prefix out from under itself; when nothing evictable
  remains, the insert is refused rather than the budget overrun.
- One cache binds to ONE (model, params) pair: segments are raw K/V
  activations. Swap params, build a new cache.

Gauges (`serving/prefix_*`): hits, misses, hit_rate, reused_tokens,
bytes (resident), bytes_saved (K/V bytes served from cache instead of
recomputed), segments, evictions — the serving runbook's first stop
(WORKFLOWS.md §13).
"""

from __future__ import annotations

import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu import knobs
from tfde_tpu.observability import metrics
from tfde_tpu.observability import trace as _trace

DEFAULT_BYTE_BUDGET = 256 * 1024 * 1024
#: single source of truth for the trie chunk AND the paged pool's block
#: granularity (TFDE_KV_BLOCK) — inference/paged.py imports this too
DEFAULT_BLOCK = knobs.env_int("TFDE_KV_BLOCK")

#: cache-collection leaves that are bookkeeping, not K/V — never cached
INDEX_LEAVES = ("cache_index", "position_index")


def leaf_name(path) -> str:
    """Stable string key for a cache-pytree leaf path — the segment-dict
    key shared between this module and server.py's warm-admission and
    primed-handoff programs."""
    return "/".join(str(getattr(k, "key", k)) for k in path)


def is_index_leaf(path) -> bool:
    return str(getattr(path[-1], "key", path[-1])) in INDEX_LEAVES


class _Node:
    """One block of one cached prefix path."""

    __slots__ = ("key", "parent", "children", "seg", "nbytes",
                 "last_used", "op")

    def __init__(self, key, parent):
        self.key = key              # tuple of `block` token ids
        self.parent = parent
        self.children: dict = {}
        self.seg: Optional[dict] = None   # leaf-name -> [block, ...] array
        self.nbytes = 0
        self.last_used = 0
        self.op = 0                 # protection stamp (current operation)


class PrefixCache:
    """Token-trie prefix-KV store with an LRU byte budget.

    Constructed standalone and handed to `ContinuousBatcher(...,
    prefix_cache=...)`, or resolved from the ``TFDE_PREFIX_CACHE``
    environment knob (see `resolve`).
    """

    def __init__(self, byte_budget: int = DEFAULT_BYTE_BUDGET,
                 block: int = DEFAULT_BLOCK,
                 registry: Optional[metrics.Registry] = None):
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        if byte_budget < 1:
            raise ValueError(
                f"byte_budget must be >= 1, got {byte_budget}"
            )
        self._root = _Node(None, None)
        self._block = int(block)
        self._budget = int(byte_budget)
        self._bytes = 0
        self._segments = 0
        self._clock = 0      # LRU timestamps (monotonic counter)
        self._op = 0         # current-operation stamp: eviction protection
        self._hits = 0
        self._misses = 0
        self._reused_tokens = 0
        self._bytes_saved = 0
        self._evictions = 0
        self._reg = registry or metrics.default_registry()

    # -- public -------------------------------------------------------------
    @property
    def block(self) -> int:
        return self._block

    @property
    def byte_budget(self) -> int:
        return self._budget

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    @property
    def segments(self) -> int:
        return self._segments

    def stats(self) -> dict:
        total = self._hits + self._misses
        return {
            "hits": self._hits,
            "misses": self._misses,
            "hit_rate": self._hits / total if total else 0.0,
            "reused_tokens": self._reused_tokens,
            "bytes": self._bytes,
            "bytes_saved": self._bytes_saved,
            "segments": self._segments,
            "evictions": self._evictions,
        }

    def lookup(self, tokens, trace: Optional[str] = None):
        """Longest cached prefix usable for prompt `tokens`.

        Returns ``(L, kv)``: L tokens of prefix (a block multiple,
        clamped so at least one suffix token remains to prefill — the
        first-token logits must come from a real forward) and
        ``kv`` = {leaf-name: [L, ...] device array}, or ``(0, None)``
        on a miss. Touches the matched path for LRU. `trace`: request
        trace id — the hit/miss + reused-token outcome lands on that
        request's distributed-trace timeline."""
        tokens = np.asarray(tokens).reshape(-1)
        p = int(tokens.size)
        self._op += 1
        usable = max((p - 1) // self._block, 0)
        node, segs = self._root, []
        while len(segs) < usable:
            b = len(segs)
            key = tuple(
                int(t) for t in tokens[b * self._block:(b + 1) * self._block]
            )
            child = node.children.get(key)
            if child is None:
                break
            segs.append(child)
            node = child
        if not segs:
            self._misses += 1
            self._publish()
            if trace is not None:
                _trace.event("serve/prefix_lookup", trace=trace,
                             hit=False, reused_tokens=0)
            return 0, None
        for s in segs:
            self._clock += 1
            s.last_used = self._clock
            s.op = self._op
        n = len(segs)
        kv = {
            name: (jnp.concatenate([s.seg[name] for s in segs], axis=0)
                   if n > 1 else segs[0].seg[name])
            for name in segs[0].seg
        }
        self._hits += 1
        self._reused_tokens += n * self._block
        self._bytes_saved += sum(s.nbytes for s in segs)
        self._publish()
        if trace is not None:
            _trace.event("serve/prefix_lookup", trace=trace, hit=True,
                         reused_tokens=n * self._block,
                         prompt_tokens=p)
        return n * self._block, kv

    def insert(self, tokens, row_cache, row: int) -> int:
        """Store the complete blocks of `tokens`' K/V from row `row` of a
        prefill-output cache. Returns the number of NEW blocks stored
        (already-resident blocks are just LRU-touched). Refuses blocks
        that cannot fit after eviction — never overruns the budget."""
        tokens = np.asarray(tokens).reshape(-1)
        nb = int(tokens.size) // self._block
        if nb == 0:
            return 0
        self._op += 1
        sliced = None   # lazily sliced only if a new node is needed
        node, created = self._root, 0
        for b in range(nb):
            key = tuple(
                int(t) for t in tokens[b * self._block:(b + 1) * self._block]
            )
            child = node.children.get(key)
            if child is None:
                if sliced is None:
                    sliced = self._slice_blocks(row_cache, row, nb)
                seg = {name: blocks[b] for name, blocks in sliced.items()}
                nbytes = sum(int(a.nbytes) for a in seg.values())
                if (self._bytes + nbytes > self._budget
                        and not self._evict(
                            self._bytes + nbytes - self._budget)):
                    break
                child = _Node(key, node)
                child.seg = seg
                child.nbytes = nbytes
                node.children[key] = child
                self._bytes += nbytes
                self._segments += 1
                created += 1
            self._clock += 1
            child.last_used = self._clock
            child.op = self._op
            node = child
        self._publish()
        return created

    # -- internals ----------------------------------------------------------
    def _slice_blocks(self, row_cache, row: int, nb: int) -> dict:
        """Per K/V leaf: row `row`'s first nb*block positions reshaped to
        [nb, block, ...] (one device op per leaf; per-block views are
        cheap slices of it)."""
        out = {}
        for path, leaf in jax.tree_util.tree_leaves_with_path(row_cache):
            if is_index_leaf(path):
                continue
            blocks = leaf[row, :nb * self._block]
            out[leaf_name(path)] = blocks.reshape(
                (nb, self._block) + tuple(leaf.shape[2:])
            )
        return out

    def _evict(self, need: int) -> bool:
        """Free >= `need` bytes by removing LRU leaf segments (childless
        nodes — interior blocks stay reachable-from-root while any
        extension lives). Nodes stamped by the current operation are
        protected. Returns False if the bytes cannot be freed. The scan
        is O(resident segments) per victim — fine at the segment counts
        a byte budget implies; swap in a heap if profiles ever say
        otherwise."""
        freed = 0
        while freed < need:
            victim, stack = None, [self._root]
            while stack:
                nxt = stack.pop()
                for child in nxt.children.values():
                    if child.children:
                        stack.append(child)
                    elif child.op != self._op and (
                            victim is None
                            or child.last_used < victim.last_used):
                        victim = child
            if victim is None:
                return False
            del victim.parent.children[victim.key]
            victim.seg = None
            freed += victim.nbytes
            self._bytes -= victim.nbytes
            self._segments -= 1
            self._evictions += 1
        return True

    def _publish(self) -> None:
        g = self._reg.gauge
        total = self._hits + self._misses
        g("serving/prefix_hits").set(self._hits)
        g("serving/prefix_misses").set(self._misses)
        g("serving/prefix_hit_rate").set(
            self._hits / total if total else 0.0
        )
        g("serving/prefix_reused_tokens").set(self._reused_tokens)
        g("serving/prefix_bytes").set(self._bytes)
        g("serving/prefix_bytes_saved").set(self._bytes_saved)
        g("serving/prefix_segments").set(self._segments)
        g("serving/prefix_evictions").set(self._evictions)
        # trie-side KV residency (observability/capacity.py's second
        # slab): how much of the trie the CURRENT op actually touched
        # (referenced) and how much eviction could reclaim right now
        # (childless segments outside the op stamp). One O(segments)
        # walk per insert/lookup — the same cost class as _evict.
        ref = evictable = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.op == self._op:
                ref += node.nbytes
            elif not node.children:
                evictable += node.nbytes
        g("kv/trie_blocks").set(self._segments)
        g("kv/trie_bytes").set(self._bytes)
        g("kv/trie_referenced_frac").set(
            ref / self._bytes if self._bytes else 0.0
        )
        g("kv/trie_evictable_bytes").set(evictable)


def resolve(spec) -> Optional[PrefixCache]:
    """Normalize the batcher's `prefix_cache=` knob.

    None (default) defers to the ``TFDE_PREFIX_CACHE`` environment
    variable: ``on``/``1`` enables with the default budget, an integer
    enables with that byte budget, anything else (including unset) is
    off — so `tools/tier1.sh` can sweep the whole suite warm without a
    single call-site change. Explicit values: False/``off`` disables,
    True/``on`` enables default budget, an int is a byte budget, and a
    `PrefixCache` instance is used as-is (shared caches are the
    caller's responsibility — one per model+params)."""
    if spec is None:
        spec = os.environ.get("TFDE_PREFIX_CACHE", "off").strip().lower()
        if spec in ("", "off", "0", "false", "no"):
            return None
        if spec in ("on", "1", "true", "yes"):
            return PrefixCache()
        try:
            return PrefixCache(byte_budget=int(spec))
        except ValueError:
            warnings.warn(
                f"TFDE_PREFIX_CACHE={spec!r} is not a recognized value "
                f"(off/on/<int byte budget>); prefix cache stays off",
                stacklevel=2,
            )
            return None
    if isinstance(spec, PrefixCache):
        return spec
    if spec in (False, 0, "off"):
        return None
    if spec in (True, "on"):
        return PrefixCache()
    if isinstance(spec, int):
        return PrefixCache(byte_budget=spec)
    raise ValueError(f"unrecognized prefix_cache spec: {spec!r}")
