"""Autoregressive generation — the serving-side capability of the causal LMs.

The reference's serving story ends at SavedModel export of a forward pass
(`/root/reference/mnist_keras_distributed.py:287-292` — classifier in, probs
out); for the token-model families this framework adds (GPT, MoE-GPT), the
forward pass alone is not servable — generation is. This module is the
TPU-native decode loop:

- **One compile, every step.** Prefill (the whole prompt in one forward) and
  the per-token decode step are two fixed-shape programs; the sampling loop
  is a `lax.scan`, so the entire generate call is ONE XLA program — no
  per-token dispatch from Python, no dynamic shapes, no recompiles as the
  sequence grows (the cache is allocated at the full budget up front and
  written by `dynamic_update_slice`, models/transformer.py decode path).
- **KV cache in the flax "cache" collection** (cached_key/cached_value/
  cache_index per attention layer + the model's position_index), threaded
  through the scan as ordinary carry state.
- **Sampling on device**: repetition penalty first (CTRL rule over a
  [B, V] presence mask carried through the scan), then greedy
  (temperature=0) or temperature, top-k (`lax.top_k` threshold) and
  nucleus/top-p (sort + exclusive-cumsum mask) — composed in that order,
  then `jax.random.categorical`.
- **EOS with static shapes**: generation always runs the full
  `max_new_tokens` scan; finished rows emit `pad_id` and stop changing. The
  returned `lengths` tells the caller where each row actually ended. (A
  data-dependent early exit would be a `while_loop` barrier on the slowest
  row — on TPU the fixed-length scan is the right trade at batch > 1.)

Sampling params (temperature/top_k/top_p/eos_id) are static arguments: a
generation config is picked once per deployment, and burning it into the
compiled program lets XLA fold the sampling graph; changing it recompiles.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _make_model_step(decode_model, params):
    """One decode forward: (cache, [B, S] tokens) -> (cache', last-position
    fp32 logits). Shared by generate / generate_ragged; beam_search wraps
    it with a log_softmax for joint-score accumulation."""

    def model_step(cache, tokens):
        logits, mutated = decode_model.apply(
            {"params": params, "cache": cache}, tokens, train=False,
            mutable=["cache"],
        )
        return mutated["cache"], logits[:, -1].astype(jnp.float32)

    return model_step


def _decode_clone(model, rolling: bool = False, paged_blocks=None,
                  kv_block=None, kv_quant=None):
    """The serving twin of a training model: decode on, remat off (remat
    only shapes the backward pass, which decode doesn't have — a training
    config with remat must not make the model unservable).

    rolling=True engages the window-bounded rolling KV cache
    (transformer.MultiHeadAttention.rolling_cache) when the model has a
    sliding window — decode memory O(window) instead of O(budget). Only
    paths that NEVER rewind the cache may pass it (generate /
    generate_ragged / beam_search); speculative decoding's rewind would
    alias committed slots.

    paged_blocks engages the paged KV pool (transformer.MultiHeadAttention
    paged_blocks/kv_block, TFDE_PAGED_KV): K/V in one shared block pool
    indexed through per-row block tables (inference/paged.py owns the
    host-side allocation). Mutually exclusive with rolling.

    kv_quant='int8' engages the quantized KV cache (TFDE_KV_QUANT): int8
    payload + per-(position, kv-head) fp32 scale sidecars in either cache
    layout, dequantized inside the attention program. 'fp'/None keep the
    full-precision cache byte-identical. Mutually exclusive with rolling
    (the modular slot rewrite has no scale plane)."""
    if not hasattr(model, "decode"):
        raise ValueError(
            f"{type(model).__name__} has no decode mode — autoregressive "
            f"generation needs a causal LM with KV-cache support (GPT)"
        )
    kw = {"decode": True}
    if getattr(model, "remat", False):
        kw["remat"] = False
    if (rolling and getattr(model, "sliding_window", None)
            and hasattr(model, "rolling_cache")):
        kw["rolling_cache"] = True
    if paged_blocks is not None:
        if rolling:
            raise ValueError(
                "paged_blocks and rolling are mutually exclusive cache "
                "layouts"
            )
        if not hasattr(model, "paged_blocks"):
            raise ValueError(
                f"{type(model).__name__} has no paged KV support — "
                f"TFDE_PAGED_KV needs a model threading paged_blocks "
                f"through its attention layers (GPT)"
            )
        kw["paged_blocks"] = int(paged_blocks)
        if kv_block is not None:
            kw["kv_block"] = int(kv_block)
    if kv_quant in ("fp", None):
        kv_quant = None  # 'fp' is the knob spelling of the default
    elif kv_quant == "int8":
        if rolling:
            raise ValueError(
                "kv_quant='int8' and rolling are mutually exclusive cache "
                "layouts (no scale plane for the modular slot rewrite)"
            )
        if not hasattr(model, "kv_quant"):
            raise ValueError(
                f"{type(model).__name__} has no quantized-KV support — "
                f"TFDE_KV_QUANT needs a model threading kv_quant through "
                f"its attention layers (GPT)"
            )
        kw["kv_quant"] = "int8"
    else:
        raise ValueError(
            f"kv_quant must be None, 'fp' or 'int8', got {kv_quant!r}"
        )
    return model.clone(**kw)


def validate_budget(model, prompt_len: int, max_new_tokens: int) -> int:
    """Shared generate/beam_search argument check; returns the total cache
    budget prompt_len + max_new_tokens.

    The max_position cap applies only to learned-position models (their wpe
    table physically ends there); rotary models have no table and may
    extrapolate past their training length — the cache budget is then
    bounded only by memory."""
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    total = prompt_len + max_new_tokens
    max_pos = getattr(model, "max_position", None)
    if (max_pos is not None and total > max_pos
            and getattr(model, "position", "learned") != "rope"):
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) = "
            f"{total} exceeds the model's max_position {max_pos}"
        )
    return total


def init_cache(model, batch_size: int, max_len: int,
               rolling: bool = False, paged_blocks=None, kv_block=None,
               kv_quant=None):
    """Zero-filled "cache" collection for `model.clone(decode=True)` sized to
    a [batch_size, max_len] generation budget (window-bounded when
    `rolling`, pool-shaped when `paged_blocks`, int8 + scale sidecars when
    `kv_quant='int8'` — must match the decode clone's flags).

    Uses `jax.eval_shape` on the decode-mode init, so no model compute (and
    no real parameter init) runs — only the cache pytree's shapes/dtypes are
    derived, then materialized as zeros.
    """
    decode_model = _decode_clone(model, rolling=rolling,
                                 paged_blocks=paged_blocks,
                                 kv_block=kv_block, kv_quant=kv_quant)
    tokens = jax.ShapeDtypeStruct((batch_size, max_len), jnp.int32)

    def _init(tokens):
        return decode_model.init(jax.random.key(0), tokens)

    shapes = jax.eval_shape(_init, tokens)
    if "cache" not in shapes:
        raise ValueError(
            f"{type(model).__name__} creates no cache variables in decode "
            f"mode — generation needs a model with decode support (GPT)"
        )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


def sample_logits(
    logits: jax.Array,
    rng: jax.Array,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    repetition_penalty: float = 1.0,
    seen: Optional[jax.Array] = None,
) -> jax.Array:
    """[B, V] logits -> [B] sampled token ids. temperature=0 is greedy
    (argmax); the top_k, top_p and min_p filters compose (k, then
    nucleus, then min-p: drop tokens whose probability is below
    min_p * max-probability — a shape-adaptive floor that cuts the long
    tail when the model is confident and keeps diversity when it is
    not).

    repetition_penalty > 1 with `seen` (a [B, V] bool presence mask of
    already-emitted ids) applies the CTRL/HF rule before any other
    processing — positive logits of seen tokens divide by the penalty,
    negative ones multiply — discouraging loops for greedy and sampled
    decoding alike."""
    if repetition_penalty <= 0.0:
        raise ValueError(
            f"repetition_penalty must be > 0 (1.0 = off), got "
            f"{repetition_penalty} — 0 would divide seen logits to inf"
        )
    logits = logits.astype(jnp.float32)
    if repetition_penalty != 1.0 and seen is not None:
        penalized = jnp.where(logits > 0, logits / repetition_penalty,
                              logits * repetition_penalty)
        logits = jnp.where(seen, penalized, logits)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    neg = jnp.finfo(jnp.float32).min
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        # exclusive cumsum: a token stays if the mass strictly above it is
        # still < top_p — the smallest set whose total reaches top_p (the
        # top-1 always stays: its exclusive mass is 0)
        cum = jnp.cumsum(probs, axis=-1) - probs
        keep_sorted = cum < top_p
        # map the per-rank decision back to vocab order via the smallest
        # kept logit (ties at the threshold keep both — harmless)
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf),
            axis=-1, keepdims=True,
        )
        logits = jnp.where(logits < threshold, neg, logits)
    if min_p is not None and 0.0 < min_p <= 1.0:
        # min_p=1.0 is MEANINGFUL (keep only tokens tied with the max) —
        # unlike top_p, 1.0 is not a no-op here
        probs = jax.nn.softmax(logits, axis=-1)
        floor = min_p * jnp.max(probs, axis=-1, keepdims=True)
        logits = jnp.where(probs < floor, neg, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "top_p", "min_p", "eos_id", "pad_id",
                     "repetition_penalty"),
)
def generate(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    repetition_penalty: float = 1.0,
):
    """Generate `max_new_tokens` continuations of `prompt` [B, P] int32.

    Returns (tokens [B, P + max_new_tokens], lengths [B]): `tokens` is the
    prompt followed by the generated continuation (post-EOS positions hold
    `pad_id`); `lengths[b]` counts prompt + generated-through-EOS.

    The whole call — prefill, scan of decode steps, sampling — is one jitted
    program; recompiles happen per (shape, sampling-config), not per token.
    Prompts are dense [B, P]: batch rows share a prompt length (bucket or
    left-trim ragged prompts; per-row validity masking would put a [B,
    max_len] mask on the attention hot path for a capability batching
    usually handles upstream).
    """
    if rng is None:
        rng = jax.random.key(0)
    b, p = prompt.shape
    total = validate_budget(model, p, max_new_tokens)
    decode_model = _decode_clone(model, rolling=True)
    cache = init_cache(model, b, total, rolling=True)
    prompt = prompt.astype(jnp.int32)
    model_step = _make_model_step(decode_model, params)
    sample = functools.partial(sample_logits, temperature=temperature,
                               top_k=top_k, top_p=top_p, min_p=min_p,
                               repetition_penalty=repetition_penalty)
    penalize = repetition_penalty != 1.0
    # presence mask of everything emitted so far (prompt included, the HF
    # convention); updated per step via a [B, V] scatter — only built when
    # the penalty is on
    vocab = model.vocab_size
    seen = (
        jnp.zeros((b, vocab), jnp.bool_).at[
            jnp.arange(b)[:, None], prompt
        ].set(True)
        if penalize else None
    )

    greedy = temperature == 0.0

    # prefill: the prompt in one fixed-shape forward
    cache, last_logits = model_step(cache, prompt)
    if greedy:
        sub = rng  # argmax path: sample_logits never reads the key
    else:
        rng, sub = jax.random.split(rng)
    tok = sample(last_logits, sub, seen=seen)
    if penalize:
        seen = seen.at[jnp.arange(b), tok].set(True)
    done = jnp.zeros((b,), jnp.bool_)
    if eos_id is not None:
        done = tok == eos_id

    def step(carry, _):
        cache, tok, rng, done, seen = carry
        cache, logits = model_step(cache, tok[:, None])
        if greedy:
            sub = rng  # greedy: skip the per-token key split on device
        else:
            rng, sub = jax.random.split(rng)
        nxt = sample(logits, sub, seen=seen)
        if eos_id is not None:
            nxt = jnp.where(done, pad_id, nxt)
            done = done | (nxt == eos_id)
        if penalize:
            seen = seen.at[jnp.arange(b), nxt].set(True)
        return (cache, nxt, rng, done, seen), nxt

    (_, _, _, done, _), rest = jax.lax.scan(
        step, (cache, tok, rng, done, seen), length=max_new_tokens - 1
    )
    new_tokens = jnp.concatenate(
        [tok[:, None], jnp.moveaxis(rest, 0, 1)], axis=1
    )  # [B, max_new_tokens]
    tokens = jnp.concatenate([prompt, new_tokens], axis=1)
    if eos_id is None:
        lengths = jnp.full((b,), total, jnp.int32)
    else:
        # a position counts while no EOS appeared strictly before it — the
        # EOS token itself is counted, post-EOS pad_id fill is not (correct
        # even when pad_id == eos_id, the GPT-2 convention)
        is_eos = (new_tokens == eos_id).astype(jnp.int32)
        seen_before = jnp.cumsum(is_eos, axis=1) - is_eos
        lengths = p + jnp.sum((seen_before == 0).astype(jnp.int32), axis=1)
    return tokens, lengths


def generate_ragged(
    model,
    params,
    prompt: jax.Array,
    prompt_lengths,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    prefill_len: Optional[int] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
):
    """`generate` for a batch of prompts with DIFFERENT lengths.

    `prompt` is [B, Pmax] RIGHT-padded; `prompt_lengths` [B] gives each
    row's real length. Returns (tokens [B, Pmax + max_new_tokens],
    lengths [B]) with row r's continuation starting at slot
    `prompt_lengths[r]`. The batch is decoded by *teacher-forcing through
    the prompt tail*: prefill covers the shortest `prefill_len` slots
    (default: min(prompt_lengths)), then every further slot is one decode
    step whose input is the row's own prompt token while the row is still
    inside its prompt and the sampled continuation after. The cache
    therefore never contains padding — positions and attention per row are
    identical to the solo run, with no per-row masks on the attention hot
    path. Under greedy decoding (temperature=0, the default) each row's
    output is EXACTLY what a solo `generate` on the unpadded row produces;
    with temperature>0 the per-token distributions match but the sampled
    draws differ (rows share one rng split per slot, and a row's k-th
    generated token lands on a different split than the solo run's k-th).

    Trade: the prompt tail beyond `prefill_len` is consumed one token per
    step instead of in one prefill forward. Bucket wildly-varying lengths
    upstream if that tail dominates.
    """
    lengths_np = np.asarray(prompt_lengths, np.int32)
    b, p_max = prompt.shape
    if lengths_np.shape != (b,):
        raise ValueError(
            f"prompt_lengths must be [batch]={b}, got {lengths_np.shape}"
        )
    if lengths_np.min() < 1 or lengths_np.max() > p_max:
        raise ValueError(
            f"prompt_lengths must lie in [1, {p_max}], got "
            f"[{lengths_np.min()}, {lengths_np.max()}]"
        )
    if prefill_len is None:
        prefill_len = int(lengths_np.min())
    if not 1 <= prefill_len <= lengths_np.min():
        raise ValueError(
            f"prefill_len={prefill_len} must lie in [1, min(prompt_lengths)="
            f"{lengths_np.min()}] — prefilling past a row's prompt would "
            f"feed its padding into the cache"
        )
    if rng is None:
        rng = jax.random.key(0)
    return _generate_ragged(
        model, params, prompt.astype(jnp.int32), jnp.asarray(lengths_np),
        max_new_tokens, rng, prefill_len, temperature, top_k, top_p,
        min_p, eos_id, pad_id,
    )


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "prefill_len", "temperature",
                     "top_k", "top_p", "min_p", "eos_id", "pad_id"),
)
def _generate_ragged(model, params, prompt, prompt_lengths, max_new_tokens,
                     rng, prefill_len, temperature, top_k, top_p, min_p,
                     eos_id, pad_id):
    b, p_max = prompt.shape
    total = validate_budget(model, p_max, max_new_tokens)
    decode_model = _decode_clone(model, rolling=True)
    cache = init_cache(model, b, total, rolling=True)
    sample = functools.partial(sample_logits, temperature=temperature,
                               top_k=top_k, top_p=top_p, min_p=min_p)
    model_step = _make_model_step(decode_model, params)

    # seq holds the final assembly; prompt slots are already right, the
    # rest starts as pad and is written slot by slot
    seq = jnp.concatenate(
        [
            jnp.where(
                jnp.arange(p_max)[None, :] < prompt_lengths[:, None],
                prompt, pad_id,
            ),
            jnp.full((b, max_new_tokens), pad_id, jnp.int32),
        ],
        axis=1,
    )
    cache, logits = model_step(cache, prompt[:, :prefill_len])

    greedy = temperature == 0.0

    def fill_slot(t, logits, rng, gen_count, done, seq):
        """Sample slot t's token (prompt token while inside the prompt,
        sampled continuation after) and write it into seq."""
        if greedy:
            sub = rng  # greedy: skip the per-slot key split on device
        else:
            rng, sub = jax.random.split(rng)
        sampled = sample(logits, sub)
        in_prompt = t < prompt_lengths  # [B]
        can_gen = (~in_prompt) & (~done) & (gen_count < max_new_tokens)
        prompt_tok = jax.lax.dynamic_slice_in_dim(seq, t, 1, axis=1)[:, 0]
        tok = jnp.where(in_prompt, prompt_tok,
                        jnp.where(can_gen, sampled, pad_id)).astype(jnp.int32)
        gen_count = gen_count + can_gen.astype(jnp.int32)
        if eos_id is not None:
            done = done | (can_gen & (sampled == eos_id))
        seq = jax.lax.dynamic_update_slice_in_dim(
            seq, tok[:, None], t, axis=1
        )
        return tok, rng, gen_count, done, seq

    def body(carry, t):
        cache, logits, rng, gen_count, done, seq = carry
        tok, rng, gen_count, done, seq = fill_slot(
            t, logits, rng, gen_count, done, seq
        )
        cache, logits = model_step(cache, tok[:, None])
        return (cache, logits, rng, gen_count, done, seq), None

    gen_count = jnp.zeros((b,), jnp.int32)
    done = jnp.zeros((b,), jnp.bool_)
    # scan stops one slot early: the final slot needs no model_step (its
    # logits would feed nothing — one whole decode forward saved per call)
    (_, logits, rng, gen_count, done, seq), _ = jax.lax.scan(
        body, (cache, logits, rng, gen_count, done, seq),
        jnp.arange(prefill_len, total - 1),
    )
    _, _, gen_count, _, seq = fill_slot(
        jnp.asarray(total - 1, jnp.int32), logits, rng, gen_count, done, seq
    )
    return seq, prompt_lengths + gen_count
