"""Admission control for the serving tiers: caps, priorities, shedding.

ROADMAP item 2 asks for "overload behavior worthy of millions of users";
without this module a traffic spike just grows the batcher's queue
unboundedly and every request's TTFT degrades together. The pieces:

- `AdmissionController`: queue-depth and queued-token-budget caps
  enforced at `submit()` time, plus a drain-rate EWMA (fed from the
  decode loop) that turns "how overloaded are we" into an honest
  `Retry-After` estimate — seconds until the backlog ahead of a new
  request would clear at the current token rate.
- Priority classes `interactive` > `batch` > `best_effort`: the
  batcher's queue drains highest-priority-first (FIFO within a class),
  the router sheds lowest-priority-first in brownout, and unlabeled
  traffic is `interactive` so existing clients see no behavior change.
- `QueueFull`: the typed rejection `submit()` raises when a cap is hit,
  carrying queue depth + the drain estimate so `ReplicaServer` can map
  it to HTTP 429 + `Retry-After` instead of a generic 500.
- `force_overload(seconds)`: the fault-injection lever
  (`resilience/faults.OverloadFault`) — while armed, every controller
  rejects as if saturated, making the overload story drillable in one
  process without generating 2x-capacity load.

Caps default OFF (0 = unlimited, from ``TFDE_ADMIT_*``): admission
control is an opt-in guardrail, and a single-tenant batcher under a
test harness must behave exactly as before this module existed.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from tfde_tpu import knobs

#: priority classes, highest first — index order IS drain order
PRIORITIES = ("interactive", "batch", "best_effort")
#: name -> rank (0 = most important); brownout sheds highest rank first
PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}
#: unlabeled traffic is interactive: pre-existing clients never get
#: brownout-shed or drained behind labeled batch work
DEFAULT_PRIORITY = "interactive"

#: HTTP header carrying the class between router and replica (the body
#: field "priority" is equivalent; the header survives primed hand-offs
#: whose body is the K/V payload)
PRIORITY_HEADER = "X-Tfde-Priority"

#: Retry-After clamp: never tell a client "come back in 0s" (thundering
#: herd) or "come back in an hour" (a drain estimate that far out is
#: noise, not a forecast)
MIN_RETRY_AFTER_S = 0.5
MAX_RETRY_AFTER_S = 60.0


def validate_priority(priority: Optional[str]) -> str:
    """Normalize a wire-supplied priority; raises ValueError on unknown
    spellings (a typo'd class silently becoming best_effort would be a
    production incident, not a convenience)."""
    if priority is None or priority == "":
        return DEFAULT_PRIORITY
    p = str(priority).strip().lower()
    if p not in PRIORITY_RANK:
        raise ValueError(
            f"unknown priority {priority!r}; expected one of {PRIORITIES}"
        )
    return p


class QueueFull(RuntimeError):
    """Typed submit() rejection: the batcher's queue is at a cap.

    Carries enough state for a well-formed 429: current queue depth,
    queued token backlog, and the drain-rate-derived retry estimate.
    Subclasses RuntimeError, so callers that predate admission control
    (and catch RuntimeError into a 400/500) stay correct; overload-aware
    callers catch QueueFull FIRST and map it to 429 + Retry-After.
    """

    def __init__(self, reason: str, queue_depth: int, queued_tokens: int,
                 retry_after_s: float, kv: Optional[dict] = None):
        self.reason = str(reason)
        self.queue_depth = int(queue_depth)
        self.queued_tokens = int(queued_tokens)
        self.retry_after_s = float(retry_after_s)
        #: KV-capacity snapshot (the batcher's kv_stats()) when the
        #: memory gate was consulted — tells a rejected client WHICH
        #: resource is scarce, not just that one is
        self.kv = dict(kv) if kv else None
        super().__init__(
            f"queue full ({self.reason}): depth={self.queue_depth}, "
            f"queued_tokens={self.queued_tokens}, retry in "
            f"~{self.retry_after_s:.1f}s"
        )

    def as_json(self) -> dict:
        """The 429 response body schema (pinned by tests/test_router.py)."""
        out = {
            "error": "queue full",
            "reason": self.reason,
            "queue_depth": self.queue_depth,
            "queued_tokens": self.queued_tokens,
            "retry_after_s": round(self.retry_after_s, 3),
        }
        if self.kv is not None:
            out["kv"] = self.kv
        return out


# -- forced overload (fault injection) ----------------------------------------
_force_lock = threading.Lock()
_forced_until = 0.0


def force_overload(seconds: float) -> None:
    """Arm the overload lever: for `seconds` every AdmissionController
    rejects as if saturated (resilience/faults.OverloadFault's hook).
    Idempotent; overlapping arms extend to the latest deadline."""
    global _forced_until
    until = time.monotonic() + float(seconds)
    with _force_lock:
        _forced_until = max(_forced_until, until)


def clear_overload() -> None:
    """Disarm a forced overload early (test teardown)."""
    global _forced_until
    with _force_lock:
        _forced_until = 0.0


def overload_active() -> bool:
    with _force_lock:
        return time.monotonic() < _forced_until


class AdmissionController:
    """Per-batcher admission policy: caps, deadline default, drain rate.

    Thread-safety: `check`/`note_drain`/`retry_after_s` are called under
    the owning `ReplicaServer.lock` (the batcher's external lock), so the
    controller itself carries no lock; the module-level forced-overload
    state has its own.

    cap semantics: 0 or None = unlimited (the default — admission control
    off). `max_queue` bounds QUEUED requests (active rows don't count:
    they are already paid for); `max_queued_tokens` bounds the queued
    output-token backlog, the unit the drain rate is measured in.
    """

    def __init__(self, max_queue: Optional[int] = None,
                 max_queued_tokens: Optional[int] = None,
                 ttft_deadline_ms: Optional[float] = None,
                 min_headroom_rows: Optional[int] = None):
        if max_queue is None:
            max_queue = knobs.env_int("TFDE_ADMIT_MAX_QUEUE", 0)
        if max_queued_tokens is None:
            max_queued_tokens = knobs.env_int(
                "TFDE_ADMIT_MAX_QUEUED_TOKENS", 0)
        if ttft_deadline_ms is None:
            ttft_deadline_ms = knobs.env_float(
                "TFDE_ADMIT_TTFT_DEADLINE_MS", 0.0)
        if min_headroom_rows is None:
            min_headroom_rows = knobs.env_int("TFDE_ADMIT_KV_HEADROOM", 0)
        self.max_queue = int(max_queue or 0)
        self.max_queued_tokens = int(max_queued_tokens or 0)
        #: memory gate: reject while the capacity model's headroom_rows
        #: is below this floor (0 = off) — admission fails on *memory*
        #: before the queue-depth proxy ever collapses
        self.min_headroom_rows = int(min_headroom_rows or 0)
        #: default TTFT deadline applied to every request that does not
        #: bring its own (0 = no deadline shedding)
        self.ttft_deadline_ms = float(ttft_deadline_ms or 0.0)
        # drain-rate EWMA, tokens/second, fed by the decode loop
        self._drain_tps = 0.0

    @property
    def enabled(self) -> bool:
        return bool(self.max_queue or self.max_queued_tokens
                    or self.min_headroom_rows)

    # -- drain rate ---------------------------------------------------------
    def note_drain(self, n_tokens: int, dt_s: float,
                   alpha: float = 0.2) -> None:
        """Fold one decode round's token output into the rate estimate."""
        if n_tokens <= 0 or dt_s <= 0:
            return
        rate = n_tokens / dt_s
        self._drain_tps = (rate if self._drain_tps == 0.0
                           else (1 - alpha) * self._drain_tps + alpha * rate)

    @property
    def drain_rate_tps(self) -> float:
        return self._drain_tps

    def retry_after_s(self, queued_tokens: int) -> float:
        """Seconds until the current backlog clears at the measured drain
        rate — the Retry-After a rejected client is told. Before the
        first decode round there is no rate; answer the clamp floor
        (an idle server's backlog clears almost immediately)."""
        if self._drain_tps <= 0.0:
            return MIN_RETRY_AFTER_S
        est = queued_tokens / self._drain_tps
        return min(max(est, MIN_RETRY_AFTER_S), MAX_RETRY_AFTER_S)

    # -- the gate -----------------------------------------------------------
    def would_reject(self, queue_depth: int, queued_tokens: int,
                     budget: int = 1,
                     headroom_rows: Optional[int] = None) -> Optional[str]:
        """The reason a request with `budget` new tokens would be
        rejected right now, or None when it would be admitted — the
        /load snapshot's `saturated` signal and `check`'s core.
        `headroom_rows` is the capacity model's current estimate (None =
        no ledger wired, memory gate silently inert)."""
        if overload_active():
            return "forced_overload"
        if self.max_queue and queue_depth >= self.max_queue:
            return "queue_depth"
        if self.max_queued_tokens and (
                queued_tokens + budget > self.max_queued_tokens):
            return "queued_tokens"
        if (self.min_headroom_rows and headroom_rows is not None
                and headroom_rows < self.min_headroom_rows):
            return "kv_headroom"
        return None

    def check(self, queue_depth: int, queued_tokens: int,
              budget: int, headroom_rows: Optional[int] = None,
              kv: Optional[dict] = None,
              drain_tokens: Optional[int] = None) -> None:
        """Admit or raise QueueFull. Called by the batcher before
        enqueue, under its external lock. `kv` (the batcher's capacity
        snapshot) rides on the rejection; `drain_tokens` is the
        outstanding decode backlog, the Retry-After basis when the
        memory gate — not queue depth — is binding (headroom frees up
        as ACTIVE rows finish, which the queued backlog alone can't
        estimate: the queue may well be empty)."""
        reason = self.would_reject(queue_depth, queued_tokens, budget,
                                   headroom_rows=headroom_rows)
        if reason is not None:
            backlog = queued_tokens + budget
            if reason == "kv_headroom" and drain_tokens:
                backlog = max(backlog, int(drain_tokens))
            raise QueueFull(reason, queue_depth, queued_tokens,
                            self.retry_after_s(backlog), kv=kv)
