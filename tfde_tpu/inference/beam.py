"""Beam-search decoding over the KV-cache decode path (inference/decode.py).

The deterministic serving mode next to sampling-based `generate`: maintain
the `num_beams` highest joint-log-prob continuations per batch row, extending
all of them one token per step through the same cached decode program.

TPU-native shape discipline: beams ride the batch dim (the model sees
[B*K, 1] tokens), the whole search is one jitted program (prefill +
`lax.scan`), and every step's beam reorder is a `jnp.take` gather of the
cache along the batch axis — a bandwidth cost that buys static shapes and
zero recompiles, the right trade on XLA.

Algorithm (the "K live beams" variant): every step scores all K*V
single-token extensions per row and keeps the top K. A beam that has
emitted `eos_id` is *finished*: it extends only with `pad_id` at zero
additional cost, so its joint score is frozen and it keeps competing for a
slot — equivalent to a finished-hypothesis set of size <= K without the
dynamic bookkeeping. Final ranking divides the joint log-prob by
`length ** length_penalty` (0.0 = no normalization; ~0.6 is the usual
translation-decoding setting).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from tfde_tpu.inference.decode import (
    _decode_clone,
    _make_model_step,
    init_cache,
    validate_budget,
)

_NEG = -1e9  # additive "impossible" — finite, so fp arithmetic stays clean


def _gather_beams(tree, idx: jax.Array, batch: int, beams: int):
    """Reorder the beam-major batch dim ([B*K, ...]) of every leaf by
    per-row beam indices idx [B, K]."""
    flat = idx + (jnp.arange(batch)[:, None] * beams)  # [B, K] global rows

    def take(x):
        if x.ndim == 0:
            return x  # scalar counters (cache_index/position_index) are
            # beam-invariant — every beam is at the same decode position
        return jnp.take(x, flat.reshape(-1), axis=0)

    return jax.tree.map(take, tree)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "num_beams",
                     "length_penalty", "eos_id", "pad_id"),
)
def beam_search(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    num_beams: int = 4,
    length_penalty: float = 0.6,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
):
    """[B, P] int32 prompt -> (tokens [B, K, P + max_new_tokens],
    scores [B, K], lengths [B, K]), beams sorted best-first by
    length-normalized joint log-prob. `tokens[:, 0]` is the decode result.
    """
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    b, p = prompt.shape
    k = num_beams
    total = validate_budget(model, p, max_new_tokens)
    decode_model = _decode_clone(model, rolling=True)
    prompt = prompt.astype(jnp.int32)

    base_step = _make_model_step(decode_model, params)

    def model_step(cache, tokens):
        # decode.py's shared step + log-softmax: beam scoring is the ONE
        # consumer that wants log-probs instead of raw logits
        cache, logits = base_step(cache, tokens)
        return cache, jax.nn.log_softmax(logits, axis=-1)  # [rows, V]

    # Prefill on [B*K, P]: all K beams of a row share the prompt, so the
    # cache starts correctly beam-expanded (a [B, P] prefill + tile of the
    # cache pytree would save K-1x prefill compute at the cost of knowing
    # the cache layout here; prefill is one forward — simplicity wins).
    cache = init_cache(model, b * k, total, rolling=True)
    expanded = jnp.repeat(prompt, k, axis=0)
    cache, logp = model_step(cache, expanded)  # logp [B*K, V]
    vocab = logp.shape[-1]

    # First step: the K beams are still identical, so pick the top-K tokens
    # of each ROW (not of K copies) to seed distinct beams.
    row_logp = logp.reshape(b, k, vocab)[:, 0]  # [B, V]
    scores, first_tok = jax.lax.top_k(row_logp, k)  # [B, K]
    live_tok = first_tok.reshape(-1)  # beam-major [B*K]
    seqs = jnp.zeros((b, k, max_new_tokens), jnp.int32)
    seqs = seqs.at[:, :, 0].set(first_tok)
    finished = (
        (first_tok == eos_id) if eos_id is not None
        else jnp.zeros((b, k), jnp.bool_)
    )

    def step(carry, t):
        cache, seqs, scores, live_tok, finished = carry
        cache, logp = model_step(cache, live_tok[:, None])  # [B*K, V]
        logp = logp.reshape(b, k, vocab)
        if eos_id is not None:
            # finished beams extend only with pad at zero cost: their joint
            # score freezes while they keep competing for a top-K slot
            pad_only = jnp.full((vocab,), _NEG).at[pad_id].set(0.0)
            logp = jnp.where(finished[:, :, None], pad_only[None, None], logp)
        cand = scores[:, :, None] + logp  # [B, K, V]
        scores, flat_idx = jax.lax.top_k(cand.reshape(b, k * vocab), k)
        beam_idx = flat_idx // vocab  # [B, K] source beam
        tok = (flat_idx % vocab).astype(jnp.int32)
        cache = _gather_beams(cache, beam_idx, b, k)
        seqs = jnp.take_along_axis(seqs, beam_idx[:, :, None], axis=1)
        seqs = seqs.at[:, :, t].set(tok)
        if eos_id is not None:
            finished = jnp.take_along_axis(finished, beam_idx, axis=1)
            finished = finished | (tok == eos_id)
        return (cache, seqs, scores, tok.reshape(-1), finished), None

    if max_new_tokens > 1:
        (cache, seqs, scores, live_tok, finished), _ = jax.lax.scan(
            step, (cache, seqs, scores, live_tok, finished),
            jnp.arange(1, max_new_tokens),
        )

    # generated length per beam: count through the first EOS, pad after
    if eos_id is None:
        lengths = jnp.full((b, k), max_new_tokens, jnp.int32)
    else:
        is_eos = (seqs == eos_id).astype(jnp.int32)
        seen_before = jnp.cumsum(is_eos, axis=-1) - is_eos
        alive = (seen_before == 0).astype(jnp.int32)
        lengths = jnp.sum(alive, axis=-1)
        seqs = jnp.where(seen_before == 0, seqs, pad_id)

    norm = jnp.asarray(lengths, jnp.float32) ** length_penalty
    final = scores / jnp.maximum(norm, 1.0)
    order = jnp.argsort(-final, axis=-1)  # best first
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    final = jnp.take_along_axis(final, order, axis=1)
    lengths = jnp.take_along_axis(lengths, order, axis=1)
    tokens = jnp.concatenate(
        [jnp.broadcast_to(prompt[:, None], (b, k, p)), seqs], axis=-1
    )
    return tokens, final, p + lengths
