"""Speculative decoding — draft-model proposal + single-forward verification.

The latency lever for serving a large model: a small DRAFT model proposes
`num_draft` tokens through its own KV-cache decode; the TARGET model
scores all of them in ONE forward. Two modes share the round skeleton:

- temperature == 0 (default): draft proposes greedily; the longest prefix
  where the target's greedy choice agrees is accepted, plus the target's
  own choice at the first disagreement (or a bonus token on full
  acceptance). Output matches plain greedy generate() token for token
  (tests/test_speculative.py asserts it), up to one caveat: the verify
  forward scores num_draft+1 positions in one GEMM where generate()
  scores one at a time, so a bf16 near-tie between the top-2 logits can
  in principle resolve differently; fp32 logits (the repo convention)
  make this a non-issue in practice.
- temperature > 0: speculative SAMPLING (Leviathan et al.) — the draft
  samples, the target accepts each proposal with min(1, p_t/p_d) and
  resamples the residual norm(max(0, p_t - p_d)) at the first rejection.
  Committed tokens are distributed exactly as target-model sampling at
  that temperature (the marginal-distribution test asserts it); draft
  quality moves only the speed.

Every round commits between 1 and num_draft+1 tokens for one target
forward — the target's per-token cost drops with the acceptance rate.

TPU shape discipline:
- The round and prefill programs are MODULE-LEVEL jits keyed on the
  (hashable) model configs and static sizes: compiled once per
  (model pair, num_draft, shapes), reused across calls — a serving loop
  pays trace+compile on the first request only.
- Both KV caches are DONATED to the round program: XLA updates them in
  place instead of copying hundreds of MB of cache per round on the
  bandwidth-bound path the optimization exists to relieve.
- Cache rewind is index surgery: rejected proposals leave stale K/V in
  both caches, but the attention validity mask reads only `cache_index`
  (models/transformer.py), so setting the index counters back makes the
  stale entries unreachable — no cache copy, no re-prefill.
- Batch > 1 rides PER-ROW cache indices: acceptance lengths diverge
  across rows, so the rewind writes a [B] index vector and the decode
  attention switches to per-row scatter writes + per-row validity masks
  (models/transformer.py `_decode_attention`, vector branch). Each row's
  committed text evolves exactly as its solo greedy run. Batch 1 keeps
  the scalar index (cheap dynamic_update_slice writes) — speculation is
  first a latency feature, and the batched path exists so a server can
  fold a few concurrent streams into one round loop.

Invariant between rounds: both caches hold K/V for exactly row r's
committed text T_r[0..m_r) (`m_r` = the rewound index counters, scalar
at batch 1, [B] above), and `tok[r]` carries the last committed token,
generated but not yet fed.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.inference.decode import (
    _decode_clone,
    init_cache,
    sample_logits,
    validate_budget,
)


def _set_index_counters(cache, value):
    """Set every layer's cache_index (and the model's position_index) to
    `value` — fed-token-count surgery. Two call modes:

    - HOST-SIDE (speculative rewind, between jitted rounds): `value` must
      be a host int / np array, NOT a jnp array — each index leaf needs
      its OWN device buffer, or the shared array would alias across the
      donated cache pytrees and trip XLA's donated-twice check.
    - TRACED (inside a jitted program, e.g. the server's fused decode
      scan): `value` may be a tracer; the leaves then share the traced
      value, which is fine — donation applies to program arguments, not
      to values inside one program."""

    def fix(path, leaf):
        name = str(getattr(path[-1], "key", path[-1]))
        if name in ("cache_index", "position_index"):
            return jnp.asarray(value, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def _assemble_round(props, n_acc, pending, num_draft: int, pad_id: int):
    """round_tokens [B, num_draft+1] = row r's accepted proposals, then its
    pending token at position n_acc[r], pad after — ONE definition for the
    greedy and sampled rounds. props [B, num_draft], n_acc/pending [B]."""
    b = props.shape[0]
    ar = jnp.arange(num_draft + 1)[None, :]
    props_ext = jnp.concatenate(
        [props, jnp.full((b, 1), pad_id, jnp.int32)], axis=1
    )
    out = jnp.where(ar < n_acc[:, None], props_ext, pad_id)
    return jnp.where(ar == n_acc[:, None], pending[:, None], out)


def _full_step(decode_model, params, cache, tokens):
    """One decode forward keeping EVERY position's fp32 logits."""
    logits, mutated = decode_model.apply(
        {"params": params, "cache": cache}, tokens, train=False,
        mutable=["cache"],
    )
    return mutated["cache"], logits.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("tgt", "drf"),
                   donate_argnums=(2, 3))
def _prefill(tgt, drf, tgt_cache, drf_cache, params, dparams, prompt):
    # both caches ingest the FULL prompt (the round feeds tok_last next,
    # so each needs K/V for everything before it)
    tgt_cache, logits = _full_step(tgt, params, tgt_cache, prompt)
    drf_cache, _ = _full_step(drf, dparams, drf_cache, prompt)
    return tgt_cache, drf_cache, logits[:, -1]  # [1, V] target logits


@functools.partial(jax.jit,
                   static_argnames=("tgt", "drf", "num_draft", "pad_id"),
                   donate_argnums=(2, 3))
def _spec_round(tgt, drf, tgt_cache, drf_cache, params, dparams, tok_last,
                num_draft, pad_id):
    """(caches, round_tokens [B, num_draft+1] pad-filled, n_new [B],
    pending [B]). round_tokens[r, :n_new[r]] = row r's accepted proposals
    + the target's token at its first disagreement (== the bonus token on
    full acceptance). Batch-generic: every row runs its own acceptance."""

    def draft_body(carry, _):
        cache, tok = carry
        cache, logits = _full_step(drf, dparams, cache, tok[:, None])
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    (drf_cache, last_prop), props = jax.lax.scan(
        draft_body, (drf_cache, tok_last), length=num_draft
    )
    props = jnp.moveaxis(props, 0, 1)  # [B, num_draft]
    # feed the final proposal too: on full acceptance its K/V must be in
    # the draft cache for the next round
    drf_cache, _ = _full_step(drf, dparams, drf_cache, last_prop[:, None])

    verify_in = jnp.concatenate([tok_last[:, None], props], axis=1)
    tgt_cache, logits = _full_step(tgt, params, tgt_cache, verify_in)
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, nd+1]
    # targets[r, i] = target's greedy choice after verify_in[r, :i+1];
    # proposal i is correct iff targets[r, i] == props[r, i]
    agree = targets[:, :num_draft] == props
    n_acc = jnp.where(
        jnp.all(agree, axis=1),
        num_draft,
        jnp.argmin(agree, axis=1),  # first False == True-prefix length
    ).astype(jnp.int32)
    # target's own token after each row's accepted prefix
    pending = jnp.take_along_axis(targets, n_acc[:, None], axis=1)[:, 0]
    out = _assemble_round(props, n_acc, pending, num_draft, pad_id)
    return tgt_cache, drf_cache, out, n_acc + 1, pending


@functools.partial(jax.jit,
                   static_argnames=("tgt", "drf", "num_draft", "pad_id",
                                    "temperature"),
                   donate_argnums=(2, 3))
def _spec_round_sampled(tgt, drf, tgt_cache, drf_cache, params, dparams,
                        tok_last, rng, num_draft, pad_id, temperature):
    """The stochastic round (Leviathan et al. speculative SAMPLING):

    the draft SAMPLES d_i ~ p_d; the target accepts d_i with probability
    min(1, p_t(d_i)/p_d(d_i)) and, at the first rejection, samples the
    replacement from the residual distribution norm(max(0, p_t - p_d)) —
    the committed tokens are then distributed EXACTLY as target-model
    sampling at this temperature (the classic correctness theorem). On
    full acceptance the bonus token samples from p_t directly. Batch-
    generic: rows draw independent uniforms/categoricals from shared key
    splits, so each row's committed stream is an independent exact sample
    of the target distribution."""
    inv_t = 1.0 / temperature

    def draft_body(carry, rng_i):
        cache, tok = carry
        cache, logits = _full_step(drf, dparams, cache, tok[:, None])
        logp = jax.nn.log_softmax(logits[:, -1] * inv_t, axis=-1)  # [B, V]
        nxt = jax.random.categorical(rng_i, logp, axis=-1).astype(jnp.int32)
        return (cache, nxt), (nxt, logp)

    rng, *step_rngs = jax.random.split(rng, num_draft + 1)
    (drf_cache, last_prop), (props, drf_logps) = jax.lax.scan(
        draft_body, (drf_cache, tok_last), jnp.stack(step_rngs)
    )
    props = jnp.moveaxis(props, 0, 1)  # [B, num_draft]
    drf_logps = jnp.moveaxis(drf_logps, 0, 1)  # [B, num_draft, V]
    drf_cache, _ = _full_step(drf, dparams, drf_cache, last_prop[:, None])

    verify_in = jnp.concatenate([tok_last[:, None], props], axis=1)
    b = verify_in.shape[0]
    tgt_cache, logits = _full_step(tgt, params, tgt_cache, verify_in)
    tgt_logps = jax.nn.log_softmax(logits * inv_t, axis=-1)  # [B, γ+1, V]

    # acceptance: u_i < p_t(d_i)/p_d(d_i); the first rejection truncates
    rng, u_rng, resid_rng, bonus_rng = jax.random.split(rng, 4)
    u = jax.random.uniform(u_rng, (b, num_draft))
    gather = lambda logps, ids: jnp.take_along_axis(
        logps, ids[..., None], axis=-1
    )[..., 0]
    ratio = jnp.exp(
        gather(tgt_logps[:, :num_draft], props)
        - gather(drf_logps, props)
    )
    accept = u < jnp.minimum(ratio, 1.0)
    n_acc = jnp.where(
        jnp.all(accept, axis=1), num_draft, jnp.argmin(accept, axis=1)
    ).astype(jnp.int32)
    # replacement at the first rejection: residual max(0, p_t - p_d),
    # renormalized; on full acceptance: sample p_t at the bonus position
    row = lambda logps, i: jnp.take_along_axis(
        logps, i[:, None, None], axis=1
    )[:, 0]
    p_t = jnp.exp(row(tgt_logps, n_acc))  # [B, V]
    p_d = jnp.exp(row(drf_logps, jnp.minimum(n_acc, num_draft - 1)))
    resid = jnp.maximum(p_t - p_d, 0.0)
    resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
    # degenerate residual (p_t <= p_d everywhere numerically) -> p_t
    resid = jnp.where(resid_sum > 0, resid / jnp.maximum(resid_sum, 1e-30),
                      p_t)
    replacement = jax.random.categorical(
        resid_rng, jnp.log(jnp.maximum(resid, 1e-30)), axis=-1
    ).astype(jnp.int32)
    bonus = jax.random.categorical(
        bonus_rng, tgt_logps[:, num_draft], axis=-1
    ).astype(jnp.int32)
    pending = jnp.where(n_acc == num_draft, bonus, replacement)
    out = _assemble_round(props, n_acc, pending, num_draft, pad_id)
    return tgt_cache, drf_cache, out, n_acc + 1, pending, rng


def generate_speculative(
    model,
    draft_model,
    params,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    num_draft: int = 4,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    return_stats: bool = False,
):
    """Generation of the TARGET model, accelerated by the draft.

    prompt is [B, P] int32 (rows share a prompt length; bucket or left-trim
    ragged prompts upstream, as for `generate`). With `temperature == 0`
    (default) each row's output matches greedy
    `generate(model, params, prompt, ...)` token for token. With
    `temperature > 0` the rounds run speculative SAMPLING: draft samples,
    the target accepts with min(1, p_t/p_d) and resamples the residual at
    the first rejection — committed tokens are distributed exactly as
    target-model sampling at that temperature, with draft quality
    affecting only the speed. Returns (tokens [B, P + max_new_tokens],
    lengths [B]).

    Batch 1 runs on the scalar shared cache index (cheapest writes); batch
    > 1 rewinds per-row [B] index vectors so acceptance lengths diverge
    independently (see module docstring). Rounds continue until every row
    is finished; finished rows ride along with frozen indices.
    """
    b, p = prompt.shape
    if num_draft < 1:
        raise ValueError(f"num_draft must be >= 1, got {num_draft}")
    total = validate_budget(model, p, max_new_tokens)
    validate_budget(draft_model, p, max_new_tokens)

    tgt = _decode_clone(model)
    drf = _decode_clone(draft_model)
    # every round feeds at most num_draft+1 tokens to each cache before the
    # rewind, so size for the final round's overshoot. Invariant (learned-
    # position models): overshoot slots can carry positions past
    # max_position; output stays correct because the wpe gather CLAMPS and
    # the overshoot tokens are ALWAYS truncated host-side before commit —
    # a change to position lookup or to the truncation below must keep
    # both halves, or clamp the last round's num_draft to the remaining
    # budget instead.
    cache_len = total + num_draft + 1
    tgt_cache = init_cache(model, b, cache_len)
    drf_cache = init_cache(draft_model, b, cache_len)
    prompt = prompt.astype(jnp.int32)

    sampled = temperature > 0.0
    if sampled and rng is None:
        rng = jax.random.key(0)
    tgt_cache, drf_cache, first_logits = _prefill(
        tgt, drf, tgt_cache, drf_cache, params, draft_params, prompt
    )
    if sampled:
        rng, sub = jax.random.split(rng)
        tok = sample_logits(first_logits, sub, temperature=temperature)
    else:
        tok = jnp.argmax(first_logits, axis=-1).astype(jnp.int32)
    tok_np = np.asarray(tok)
    out_tokens = [[int(t)] for t in tok_np]  # per-row committed stream
    # committed[r]: tokens whose K/V both caches hold for row r; the last
    # element of out_tokens[r] is pending (generated, not yet fed)
    committed = np.full((b,), p, np.int64)
    done = np.zeros((b,), bool)
    if eos_id is not None:
        done |= tok_np == eos_id
    rounds = 0

    def _active(r):
        return not done[r] and len(out_tokens[r]) < max_new_tokens

    while any(_active(r) for r in range(b)):
        rounds += 1
        # batch 1 keeps the scalar index (dynamic_update_slice writes);
        # batch > 1 rewinds a [B] vector, flipping the decode attention to
        # its per-row scatter branch (one extra trace on the first round)
        # host-side values (int / np.ndarray), NOT jnp arrays: every index
        # leaf must become its OWN device buffer — a shared jnp array would
        # alias across the two donated cache pytrees and trip XLA's
        # donate-the-same-buffer-twice check
        rewind = int(committed[0]) if b == 1 else committed.astype(np.int32)
        tgt_cache = _set_index_counters(tgt_cache, rewind)
        drf_cache = _set_index_counters(drf_cache, rewind)
        if sampled:
            (tgt_cache, drf_cache, round_toks, n_new, tok,
             rng) = _spec_round_sampled(
                tgt, drf, tgt_cache, drf_cache, params, draft_params, tok,
                rng, num_draft, pad_id, temperature,
            )
        else:
            tgt_cache, drf_cache, round_toks, n_new, tok = _spec_round(
                tgt, drf, tgt_cache, drf_cache, params, draft_params, tok,
                num_draft, pad_id,
            )
        round_np = np.asarray(round_toks)  # [B, num_draft+1]
        n_np = np.asarray(n_new)
        for r in range(b):
            if not _active(r):
                continue
            toks = round_np[r, : int(n_np[r])].tolist()
            if eos_id is not None and eos_id in toks:
                toks = toks[: toks.index(eos_id) + 1]
                done[r] = True
            toks = toks[: max_new_tokens - len(out_tokens[r])]
            committed[r] += len(toks)  # tok_last + accepted (pending unfed)
            out_tokens[r].extend(toks)
        tok = jnp.asarray([row[-1] for row in out_tokens], jnp.int32)

    new = np.full((b, max_new_tokens), pad_id, np.int64)
    for r in range(b):
        new[r, : len(out_tokens[r])] = out_tokens[r]
    tokens = np.concatenate([np.asarray(prompt), new], axis=1).astype(
        np.int32
    )
    lengths = np.asarray([p + len(row) for row in out_tokens], np.int32)
    if return_stats:
        generated = sum(len(row) for row in out_tokens)
        stats = {
            "rounds": rounds,
            "generated": generated,
            # the prefill contributes each row's first token without a
            # round; a run with zero rounds reports 0.0 (no acceptance
            # information), never a fake 1.0 that would skew a dashboard's
            # average. Batch > 1 averages over rows (rows share rounds).
            "tokens_per_round": (
                (generated - b) / (rounds * b) if rounds else 0.0
            ),
        }
        return tokens, lengths, stats
    return tokens, lengths
