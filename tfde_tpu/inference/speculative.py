"""Speculative decoding — draft-model proposal + single-forward verification.

The latency lever for serving a large model: a small DRAFT model proposes
`num_draft` tokens through its own KV-cache decode; the TARGET model
scores all of them in ONE forward. Two modes share the round skeleton:

- temperature == 0 (default): draft proposes greedily; the longest prefix
  where the target's greedy choice agrees is accepted, plus the target's
  own choice at the first disagreement (or a bonus token on full
  acceptance). Output matches plain greedy generate() token for token
  (tests/test_speculative.py asserts it), up to one caveat: the verify
  forward scores num_draft+1 positions in one GEMM where generate()
  scores one at a time, so a bf16 near-tie between the top-2 logits can
  in principle resolve differently; fp32 logits (the repo convention)
  make this a non-issue in practice.
- temperature > 0: speculative SAMPLING (Leviathan et al.) — the draft
  samples, the target accepts each proposal with min(1, p_t/p_d) and
  resamples the residual norm(max(0, p_t - p_d)) at the first rejection.
  Committed tokens are distributed exactly as target-model sampling at
  that temperature (the marginal-distribution test asserts it); draft
  quality moves only the speed.

Every round commits between 1 and num_draft+1 tokens for one target
forward — the target's per-token cost drops with the acceptance rate.

TPU shape discipline:
- The round and prefill programs are MODULE-LEVEL jits keyed on the
  (hashable) model configs and static sizes: compiled once per
  (model pair, num_draft, shapes), reused across calls — a serving loop
  pays trace+compile on the first request only.
- Both KV caches are DONATED to the round program: XLA updates them in
  place instead of copying hundreds of MB of cache per round on the
  bandwidth-bound path the optimization exists to relieve.
- Cache rewind is scalar surgery: rejected proposals leave stale K/V in
  both caches, but the attention validity mask reads only `cache_index`
  (models/transformer.py), so setting the index counters back makes the
  stale entries unreachable — no cache copy, no re-prefill.
- Batch is 1 by design: `cache_index` is shared across rows and per-row
  acceptance lengths diverge — classic speculative decoding is a latency
  optimization for single-stream serving (batch throughput is already
  served by `generate`).

Invariant between rounds: both caches hold K/V for exactly the committed
text T[0..m) (`m` = the rewound index counters), and `tok` carries the
last committed token T[m], generated but not yet fed.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.inference.decode import (
    _decode_clone,
    init_cache,
    sample_logits,
    validate_budget,
)


def _set_index_counters(cache, value):
    """Rewind every layer's cache_index (and the model's position_index)
    to `value` — fed-token-count surgery after a partial acceptance."""

    def fix(path, leaf):
        name = str(getattr(path[-1], "key", path[-1]))
        if name in ("cache_index", "position_index"):
            return jnp.asarray(value, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def _assemble_round(props, n_acc, pending, num_draft: int, pad_id: int):
    """round_tokens [num_draft+1] = accepted proposals, then the pending
    token at position n_acc, pad after — ONE definition for the greedy and
    sampled rounds."""
    return jnp.where(
        jnp.arange(num_draft + 1) < n_acc,
        jnp.concatenate([props, jnp.array([pad_id], jnp.int32)]),
        pad_id,
    ).at[n_acc].set(pending)


def _full_step(decode_model, params, cache, tokens):
    """One decode forward keeping EVERY position's fp32 logits."""
    logits, mutated = decode_model.apply(
        {"params": params, "cache": cache}, tokens, train=False,
        mutable=["cache"],
    )
    return mutated["cache"], logits.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("tgt", "drf"),
                   donate_argnums=(2, 3))
def _prefill(tgt, drf, tgt_cache, drf_cache, params, dparams, prompt):
    # both caches ingest the FULL prompt (the round feeds tok_last next,
    # so each needs K/V for everything before it)
    tgt_cache, logits = _full_step(tgt, params, tgt_cache, prompt)
    drf_cache, _ = _full_step(drf, dparams, drf_cache, prompt)
    return tgt_cache, drf_cache, logits[:, -1]  # [1, V] target logits


@functools.partial(jax.jit,
                   static_argnames=("tgt", "drf", "num_draft", "pad_id"),
                   donate_argnums=(2, 3))
def _spec_round(tgt, drf, tgt_cache, drf_cache, params, dparams, tok_last,
                num_draft, pad_id):
    """(caches, round_tokens [num_draft+1] pad-filled, n_new, pending).
    round_tokens[:n_new] = accepted proposals + the target's token at the
    first disagreement (== the bonus token on full acceptance)."""

    def draft_body(carry, _):
        cache, tok = carry
        cache, logits = _full_step(drf, dparams, cache, tok[:, None])
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    (drf_cache, last_prop), props = jax.lax.scan(
        draft_body, (drf_cache, tok_last), length=num_draft
    )
    props = jnp.moveaxis(props, 0, 1)[0]  # [num_draft]
    # feed the final proposal too: on full acceptance its K/V must be in
    # the draft cache for the next round
    drf_cache, _ = _full_step(drf, dparams, drf_cache, last_prop[:, None])

    verify_in = jnp.concatenate([tok_last, props], axis=0)[None, :]
    tgt_cache, logits = _full_step(tgt, params, tgt_cache, verify_in)
    targets = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
    # targets[i] = target's greedy choice after verify_in[:, :i+1];
    # proposal i is correct iff targets[i] == props[i]
    agree = targets[:num_draft] == props
    n_acc = jnp.where(
        jnp.all(agree),
        num_draft,
        jnp.argmin(agree),  # index of the first False == True-prefix length
    ).astype(jnp.int32)
    pending = targets[n_acc]  # target's own token after the prefix
    out = _assemble_round(props, n_acc, pending, num_draft, pad_id)
    return tgt_cache, drf_cache, out, n_acc + 1, pending[None]


@functools.partial(jax.jit,
                   static_argnames=("tgt", "drf", "num_draft", "pad_id",
                                    "temperature"),
                   donate_argnums=(2, 3))
def _spec_round_sampled(tgt, drf, tgt_cache, drf_cache, params, dparams,
                        tok_last, rng, num_draft, pad_id, temperature):
    """The stochastic round (Leviathan et al. speculative SAMPLING):

    the draft SAMPLES d_i ~ p_d; the target accepts d_i with probability
    min(1, p_t(d_i)/p_d(d_i)) and, at the first rejection, samples the
    replacement from the residual distribution norm(max(0, p_t - p_d)) —
    the committed tokens are then distributed EXACTLY as target-model
    sampling at this temperature (the classic correctness theorem). On
    full acceptance the bonus token samples from p_t directly."""
    inv_t = 1.0 / temperature

    def draft_body(carry, rng_i):
        cache, tok = carry
        cache, logits = _full_step(drf, dparams, cache, tok[:, None])
        logp = jax.nn.log_softmax(logits[:, -1] * inv_t, axis=-1)  # [1, V]
        nxt = jax.random.categorical(rng_i, logp, axis=-1).astype(jnp.int32)
        return (cache, nxt), (nxt, logp[0])

    rng, *step_rngs = jax.random.split(rng, num_draft + 1)
    (drf_cache, last_prop), (props, drf_logps) = jax.lax.scan(
        draft_body, (drf_cache, tok_last), jnp.stack(step_rngs)
    )
    props = jnp.moveaxis(props, 0, 1)[0]  # [num_draft]
    drf_cache, _ = _full_step(drf, dparams, drf_cache, last_prop[:, None])

    verify_in = jnp.concatenate([tok_last, props], axis=0)[None, :]
    tgt_cache, logits = _full_step(tgt, params, tgt_cache, verify_in)
    tgt_logps = jax.nn.log_softmax(logits[0] * inv_t, axis=-1)  # [γ+1, V]

    # acceptance: u_i < p_t(d_i)/p_d(d_i); the first rejection truncates
    rng, u_rng, resid_rng, bonus_rng = jax.random.split(rng, 4)
    u = jax.random.uniform(u_rng, (num_draft,))
    ratio = jnp.exp(
        tgt_logps[jnp.arange(num_draft), props]
        - drf_logps[jnp.arange(num_draft), props]
    )
    accept = u < jnp.minimum(ratio, 1.0)
    n_acc = jnp.where(
        jnp.all(accept), num_draft, jnp.argmin(accept)
    ).astype(jnp.int32)
    # replacement at the first rejection: residual max(0, p_t - p_d),
    # renormalized; on full acceptance: sample p_t at the bonus position
    p_t = jnp.exp(tgt_logps[n_acc])
    p_d = jnp.exp(drf_logps[jnp.minimum(n_acc, num_draft - 1)])
    resid = jnp.maximum(p_t - p_d, 0.0)
    resid_sum = jnp.sum(resid)
    # degenerate residual (p_t <= p_d everywhere numerically) -> p_t
    resid = jnp.where(resid_sum > 0, resid / jnp.maximum(resid_sum, 1e-30),
                      p_t)
    replacement = jax.random.categorical(
        resid_rng, jnp.log(jnp.maximum(resid, 1e-30))
    ).astype(jnp.int32)
    bonus = jax.random.categorical(bonus_rng, tgt_logps[num_draft]).astype(
        jnp.int32
    )
    pending = jnp.where(n_acc == num_draft, bonus, replacement)
    out = _assemble_round(props, n_acc, pending, num_draft, pad_id)
    return tgt_cache, drf_cache, out, n_acc + 1, pending[None], rng


def generate_speculative(
    model,
    draft_model,
    params,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    num_draft: int = 4,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    return_stats: bool = False,
):
    """Generation of the TARGET model, accelerated by the draft.

    prompt is [1, P] int32 (single stream — see module docstring). With
    `temperature == 0` (default) the output matches greedy
    `generate(model, params, prompt, ...)` token for token. With
    `temperature > 0` the rounds run speculative SAMPLING: draft samples,
    the target accepts with min(1, p_t/p_d) and resamples the residual at
    the first rejection — committed tokens are distributed exactly as
    target-model sampling at that temperature, with draft quality
    affecting only the speed. Returns (tokens [1, P + max_new_tokens],
    lengths [1]).
    """
    b, p = prompt.shape
    if b != 1:
        raise ValueError(
            f"speculative decoding is single-stream (batch 1), got batch "
            f"{b} — cache_index is shared across rows and per-row "
            f"acceptance diverges; use generate() for batch throughput"
        )
    if num_draft < 1:
        raise ValueError(f"num_draft must be >= 1, got {num_draft}")
    total = validate_budget(model, p, max_new_tokens)
    validate_budget(draft_model, p, max_new_tokens)

    tgt = _decode_clone(model)
    drf = _decode_clone(draft_model)
    # every round feeds at most num_draft+1 tokens to each cache before the
    # rewind, so size for the final round's overshoot
    cache_len = total + num_draft + 1
    tgt_cache = init_cache(model, 1, cache_len)
    drf_cache = init_cache(draft_model, 1, cache_len)
    prompt = prompt.astype(jnp.int32)

    sampled = temperature > 0.0
    if sampled and rng is None:
        rng = jax.random.key(0)
    tgt_cache, drf_cache, first_logits = _prefill(
        tgt, drf, tgt_cache, drf_cache, params, draft_params, prompt
    )
    if sampled:
        rng, sub = jax.random.split(rng)
        tok = sample_logits(first_logits, sub, temperature=temperature)
    else:
        tok = jnp.argmax(first_logits, axis=-1).astype(jnp.int32)
    out_tokens = [int(tok[0])]
    committed = p  # tokens whose K/V both caches hold; `tok` is pending
    done = eos_id is not None and out_tokens[0] == eos_id
    rounds = 0
    while len(out_tokens) < max_new_tokens and not done:
        rounds += 1
        tgt_cache = _set_index_counters(tgt_cache, committed)
        drf_cache = _set_index_counters(drf_cache, committed)
        if sampled:
            (tgt_cache, drf_cache, round_toks, n_new, tok,
             rng) = _spec_round_sampled(
                tgt, drf, tgt_cache, drf_cache, params, draft_params, tok,
                rng, num_draft, pad_id, temperature,
            )
        else:
            tgt_cache, drf_cache, round_toks, n_new, tok = _spec_round(
                tgt, drf, tgt_cache, drf_cache, params, draft_params, tok,
                num_draft, pad_id,
            )
        toks = np.asarray(round_toks)[: int(n_new)].tolist()
        if eos_id is not None and eos_id in toks:
            toks = toks[: toks.index(eos_id) + 1]
            done = True
        toks = toks[: max_new_tokens - len(out_tokens)]
        committed += len(toks)  # tok_last + accepted (pending stays unfed)
        out_tokens.extend(toks)
        tok = jnp.asarray([out_tokens[-1]], jnp.int32)

    new = np.full((max_new_tokens,), pad_id, np.int64)
    new[: len(out_tokens)] = out_tokens
    tokens = np.concatenate([np.asarray(prompt)[0], new]).astype(np.int32)
    lengths = np.asarray([p + len(out_tokens)], np.int32)
    if return_stats:
        generated = len(out_tokens)
        stats = {
            "rounds": rounds,
            "generated": generated,
            # the prefill contributes the first token without a round; a
            # run with zero rounds reports 0.0 (no acceptance information),
            # never a fake 1.0 that would skew a dashboard's average
            "tokens_per_round": (
                (generated - 1) / rounds if rounds else 0.0
            ),
        }
        return tokens[None], lengths, stats
    return tokens[None], lengths
