"""Continuous batching — the serving loop that keeps every batch row busy.

`generate` (inference/decode.py) serves one batch to completion: rows that
finish early ride along as padding until the slowest row ends, and new
requests wait for the whole batch. A serving deployment wants the modern
alternative: a FIXED decode batch where a finished row is immediately
re-used for the next queued request while the other rows keep decoding —
continuous batching (the vLLM/Orca scheduling idea, re-built on this
framework's primitives).

What makes it cheap here: the per-row KV-cache machinery built for
batched speculative decoding (models/transformer.py `_decode_attention`
vector branch + per-row `position_index`) already lets every batch row
sit at a DIFFERENT sequence position with its own validity horizon.
Admission is then per-row cache surgery:

- one compiled DECODE SCAN serves the whole batch for K ticks: the model
  forward, the sampler (temperature/top-k/top-p/min-p/repetition
  penalty, `seen`-mask update included), per-row EOS/budget masking and
  index bookkeeping all live inside ONE jitted `lax.scan`, so the host
  pays one dispatch and one sync per K tokens per row instead of three
  or more per token (the 97x serve-vs-decode gap BENCH_r05 measured was
  exactly this host overhead);
- finished rows freeze mid-scan: they feed `pad_id`, their index stops
  advancing, and their sampled output is masked — on-device, no host
  round-trip (a frozen row's final pad writes land beyond its committed
  count and stay unreachable, the stale-K/V invariant);
- one compiled PREFILL per distinct prompt BUCKET admits every freed row
  of that bucket at once ([R, Pbucket] prompts, first tokens sampled
  inside the same program), and one multi-row cache scatter lands all of
  them (`.at[rows].set`) — admission cost amortizes over the wave
  instead of paying a prefill + scatter round-trip per row;
- EOS, budget, and queue bookkeeping are per-row host state, replayed
  from the scan's [B, K] token/emitted output after the single fetch.

Greedy determinism: each request's output equals a solo
`generate(model, params, prompt)` run token for token regardless of what
shares the batch or the scan depth K (rows are independent through
attention's per-row validity masks; tests/test_server.py asserts it
across staggered admissions and scan depths). Temperature>0 draws ride a
shared key stream — distributionally correct per request, draw values
batch-dependent.

Scan-depth adaptation: `scan_depth` is the K ceiling. When the queue is
non-empty K drops toward the soonest row completion (host-known budget;
EOS is not host-predictable) so a freed row admits without waiting out a
long scan; when the queue is empty K is capped by the longest remaining
budget so a draining batch never runs dead ticks. K is chosen from the
power-of-two ladder {1, 2, 4, ..., scan_depth} to bound compile count at
O(log scan_depth).

Prompt-length compiles: prompts are right-padded to the smallest of
`prompt_buckets` that fits (powers of two up to max_len by default), so
the prefill compiles once per BUCKET (x the power-of-two wave-size
ladder), not per length — the first-token logits are read at each row's
true last position, and the admission-time index rewind makes the pad
K/V unreachable.
"""

from __future__ import annotations

import collections
import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.inference.decode import (
    _decode_clone,
    init_cache,
    sample_logits,
    validate_budget,
)
from tfde_tpu.inference.speculative import _set_index_counters
from tfde_tpu.observability import metrics
from tfde_tpu.observability.spans import span


def _fetch(tree):
    """THE host sync: one blocking device->host fetch for everything the
    host loop needs this round. Kept as a module-level seam so tests can
    count syncs (tests/test_server.py's dispatch-budget regression guard)
    and so no call site is tempted to sprinkle per-array np.asarray
    fetches back onto the hot path."""
    return jax.device_get(tree)


@functools.partial(
    jax.jit,
    static_argnames=("model", "depth", "temperature", "top_k", "top_p",
                     "min_p", "repetition_penalty", "eos_id", "pad_id"),
    donate_argnums=(1, 3, 4, 5, 6, 7),
)
def _decode_scan(model, cache, params, tok, idx, budget, done, seen, rng,
                 depth, temperature, top_k, top_p, min_p,
                 repetition_penalty, eos_id, pad_id):
    """K = `depth` fused decode ticks for the whole batch, device-resident.

    Carry per row r: `tok[r]` the pending (sampled, unfed) token, `idx[r]`
    the committed token count (cache index), `budget[r]` remaining output
    tokens, `done[r]` frozen flag, plus the optional [B, V] `seen`
    presence mask and the sampling key. Each tick feeds the pending
    token, samples the next one with the FULL sampling config in-program
    (no separate sample_logits dispatch, no host `.at[]` seen update),
    and applies EOS/budget masking on device: a finishing row emits its
    last token, flips `done`, and thereafter feeds `pad_id` with a frozen
    index (its pad K/V lands beyond the committed count — unreachable).

    Returns (cache, tok, idx, budget, done, seen, rng, toks [B, K],
    emitted [B, K]): `toks[r]` masked to `pad_id` where not emitted;
    `emitted[r]` is a True-prefix per row (rows freeze monotonically), so
    the host replays exactly `emitted[r].sum()` tokens into its
    bookkeeping after the ONE fetch.

    The greedy path (temperature == 0.0) carries `rng=None` and performs
    no `jax.random.split` at all — dead device work the per-tick loop
    used to pay on every step.
    """

    def body(carry, _):
        cache, tok, idx, budget, done, seen, rng = carry
        # index surgery each tick instead of trusting the model's own
        # advance: frozen rows must NOT advance, and writing the [B]
        # vector here keeps the carry shape stable from tick one
        cache = _set_index_counters(cache, idx)
        feed = jnp.where(done, jnp.int32(pad_id), tok)
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, feed[:, None], train=False,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        logits = logits[:, -1].astype(jnp.float32)
        if temperature != 0.0:
            rng, sub = jax.random.split(rng)
        else:
            sub = rng  # greedy: sample_logits is argmax, rng untouched
        nxt = sample_logits(
            logits, sub, temperature=temperature, top_k=top_k, top_p=top_p,
            min_p=min_p, repetition_penalty=repetition_penalty, seen=seen,
        )
        live = ~done
        nxt = jnp.where(done, jnp.int32(pad_id), nxt)
        if seen is not None:
            ar = jnp.arange(nxt.shape[0])
            seen = jnp.where(done[:, None], seen,
                             seen.at[ar, nxt].set(True))
        # feeding tok committed it; the new sample is now pending
        idx = idx + live.astype(jnp.int32)
        budget = budget - live.astype(jnp.int32)
        fin = budget <= 0
        if eos_id is not None:
            fin = fin | (nxt == eos_id)
        done = done | (live & fin)
        tok = jnp.where(live, nxt, tok)
        return (cache, tok, idx, budget, done, seen, rng), (nxt, live)

    carry = (cache, tok, idx, budget, done, seen, rng)
    carry, (toks, emitted) = jax.lax.scan(body, carry, length=depth)
    cache, tok, idx, budget, done, seen, rng = carry
    return (cache, tok, idx, budget, done, seen, rng,
            jnp.moveaxis(toks, 0, 1), jnp.moveaxis(emitted, 0, 1))


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "top_k", "top_p", "min_p",
                     "repetition_penalty"),
)
def _prefill_rows(model, row_cache, params, prompts, last, valid, rng,
                  temperature, top_k, top_p, min_p, repetition_penalty):
    """Prefill R rows of one bucket in ONE call and sample each row's
    first token inside the same program.

    prompts: [R, Pbucket] right-padded prompt batch; `last` [R] the true
    last position per row (so bucketing never changes the first sampled
    token); `valid` [R, Pbucket] marks real (non-pad) prompt positions —
    only consulted when the repetition penalty is on, where it keeps pad
    slots out of the presence mask. Compiled per (bucket length, wave
    size); the admission ladder pads the wave to a power of two by
    REPEATING a real row (identical content, so the duplicate scatter
    writes are idempotent) to bound compile count.

    Returns (filled row cache, first tokens [R], seen rows [R, V] or
    None). Pad correctness rides the per-row index machinery: pad K/V
    lands beyond each row's committed count once the admission rewind
    sets it to the TRUE prompt length."""
    logits, mutated = model.apply(
        {"params": params, "cache": row_cache}, prompts, train=False,
        mutable=["cache"],
    )
    r = prompts.shape[0]
    ar = jnp.arange(r)
    logits = logits[ar, last].astype(jnp.float32)
    row_seen = None
    if repetition_penalty != 1.0:
        hits = jnp.zeros((r, model.vocab_size), jnp.int32)
        hits = hits.at[ar[:, None], prompts].add(valid.astype(jnp.int32))
        row_seen = hits > 0
    tok = sample_logits(
        logits, rng, temperature=temperature, top_k=top_k, top_p=top_p,
        min_p=min_p, repetition_penalty=repetition_penalty, seen=row_seen,
    )
    if row_seen is not None:
        row_seen = row_seen.at[ar, tok].set(True)
    return mutated["cache"], tok, row_seen


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(cache, rows_cache, rows):
    """Write an R-row prefill cache's K/V leaves into batch rows `rows`
    ([R] int32) in ONE donated update — the multi-row generalization of
    the old per-row `.at[row].set` round-trip. Index counters pass
    through (the decode scan rewrites them from the host's committed
    counts every tick). Wave padding duplicates a real row verbatim, so
    duplicate indices in `rows` write identical values and the scatter
    stays deterministic."""

    def merge(path, big, small):
        name = str(getattr(path[-1], "key", path[-1]))
        if name in ("cache_index", "position_index"):
            return big
        return big.at[rows].set(small.astype(big.dtype))

    return jax.tree_util.tree_map_with_path(merge, cache, rows_cache)


def _normalize_buckets(buckets, max_len: int) -> tuple:
    """Sorted prefill bucket lengths; default powers of two up to
    max_len. Every prompt pads up to the smallest bucket that fits."""
    if buckets is None:
        buckets, b = [], 8
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
    # clamp to max_len: a larger bucket would pad past the row cache and
    # fail at ADMISSION (after the request left the queue), not here
    out = tuple(sorted({min(int(b), max_len) for b in buckets}))
    if not out or out[-1] < max_len:
        raise ValueError(
            f"prompt_buckets must cover max_len {max_len}; got {out}"
        )
    return out


def _bucketed(prompt: np.ndarray, buckets: tuple, pad_id: int):
    """(padded [1, bucket] int32 prompt, true-last-position index)."""
    p = prompt.size
    bucket = next(b for b in buckets if b >= p)
    padded = np.full((1, bucket), pad_id, np.int32)
    padded[0, :p] = prompt
    return jnp.asarray(padded), p - 1


def _ladder_depth(cap: int, bound: int) -> int:
    """Scan depth for this round: the largest value from the ladder
    {1, 2, 4, ..., cap} (cap always included) that is <= bound. Host
    bookkeeping picks `bound` from remaining budgets, so compiles stay
    O(log cap) while K still shrinks to 1 near a row completion."""
    bound = min(cap, max(1, bound))
    if bound >= cap:
        return cap
    k = 1
    while k * 2 <= bound:
        k *= 2
    return k


def _pad_wave(r: int, cap: int) -> int:
    """Admission wave sizes ride their own power-of-two ladder (capped at
    the batch size) so `_prefill_rows` compiles O(log B) per bucket, not
    O(B)."""
    k = 1
    while k < r:
        k *= 2
    return min(k, cap)


class _BatcherBase:
    """Machinery shared by `ContinuousBatcher` and
    `SpeculativeContinuousBatcher`: the request queue, per-row host
    bookkeeping (`_take_token`), batched bucket admission (`_admit`
    drives the subclass `_prefill_wave` hook), stats publication, and
    the dispatch/sync accounting the bench and the regression-guard test
    read back.

    Invariant per active row r (the speculative-decoding contract): the
    cache holds K/V for exactly `committed[r]` tokens and `tok[r]` is the
    last generated-but-unfed token.
    """

    _metrics_prefix = "serving/batcher"

    def __init__(self, model, params, batch_size: int, max_len: int,
                 eos_id, pad_id: int, rng, prompt_buckets):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._buckets = _normalize_buckets(prompt_buckets, max_len)
        self._model = model
        self._params = params
        self._b = batch_size
        self._max_len = int(max_len)
        self._eos = eos_id
        self._pad = pad_id
        self._rng = rng if rng is not None else jax.random.key(0)

        self._req = [None] * batch_size          # request id or None
        self._out = [[] for _ in range(batch_size)]
        self._budget = np.zeros(batch_size, np.int64)
        self._committed = np.zeros(batch_size, np.int64)
        self._tok = np.full(batch_size, pad_id, np.int64)
        self._queue: collections.deque = collections.deque()
        self._submitted_at: dict = {}   # rid -> submit wall time (TTFT)
        self._next_id = 0
        self._rounds = 0         # decode ticks run
        self._generated = 0      # every delivered token (incl. prefill 1st)
        self._dispatches = 0     # jitted-program / eager-op invocations
        self._syncs = 0          # blocking device->host fetches

    # -- public -------------------------------------------------------------
    @property
    def idle(self) -> bool:
        return not self._queue and all(r is None for r in self._req)

    @property
    def free_rows(self) -> int:
        return sum(r is None for r in self._req)

    def submit(self, prompt, max_new_tokens: int) -> int:
        """Queue a request; returns its id. prompt: 1-D int token ids."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens > self._max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the batcher's max_len "
                f"{self._max_len}"
            )
        self._validate_submit(prompt, max_new_tokens)
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, prompt, int(max_new_tokens)))
        self._submitted_at[rid] = time.perf_counter()
        return rid

    def run(self) -> list:
        """Step until idle; returns every completion in finish order."""
        done = []
        while not self.idle:
            done.extend(self.step())
        return done

    def serve_metrics(self, port: int = 0, aggregator=None):
        """Start a /metrics endpoint next to this batcher (exposition.py);
        returns the MetricsServer (read `.port` back when port=0). Pass a
        ClusterAggregator to also accept worker pushes at /push — the
        multi-host serving deployment's one-scrape fleet view."""
        from tfde_tpu.observability.exposition import serve_metrics

        return serve_metrics(port=port, aggregator=aggregator)

    def _publish_stats(self) -> None:
        """Mirror stats() into the metric registry so serving throughput
        rides the /metrics and JSONL exposition paths."""
        reg = metrics.default_registry()
        for k, v in self.stats().items():
            reg.gauge(f"{self._metrics_prefix}/{k}").set(v)
        reg.gauge(f"{self._metrics_prefix}/queue_depth").set(len(self._queue))
        reg.gauge(f"{self._metrics_prefix}/free_rows").set(self.free_rows)

    # -- hooks --------------------------------------------------------------
    def _validate_submit(self, prompt: np.ndarray,
                         max_new_tokens: int) -> None:
        validate_budget(self._model, int(prompt.size), max_new_tokens)

    def _prefill_wave(self, prompts: np.ndarray, last: np.ndarray,
                      rows: np.ndarray, plens: np.ndarray,
                      n: int) -> np.ndarray:
        """Prefill + scatter one padded admission wave; returns the [R]
        first sampled tokens (host ints). Rows past `n` are ladder
        padding (duplicates of row 0). Subclass-specific: which model(s),
        which caches, which sampling config."""
        raise NotImplementedError

    # -- internals ----------------------------------------------------------
    def _take_token(self, r: int, t: int) -> list:
        """Record a sampled token for row r; frees the row on completion."""
        self._out[r].append(t)
        self._budget[r] -= 1
        self._tok[r] = t
        self._generated += 1
        if self._budget[r] <= 0 or (self._eos is not None and t == self._eos):
            done = (self._req[r], np.asarray(self._out[r], np.int32))
            self._req[r] = None
            self._out[r] = []
            self._committed[r] = 0
            self._tok[r] = self._pad
            return [done]
        return []

    def _admit(self) -> list:
        """Fill free rows from the queue, a BUCKET WAVE at a time: every
        freed row whose next request shares a prompt bucket prefills in
        one [R, Pbucket] call and lands with one multi-row scatter. The
        prefill samples each row's first token in-program (generate's
        prefill contract), so every active row uniformly holds one
        pending token afterwards. A request finishing on its first token
        (budget 1 / instant EOS) frees its row for the next queued
        request within the same call."""
        finished = []
        reg = metrics.default_registry()
        while self._queue and self.free_rows:
            free = [r for r in range(self._b) if self._req[r] is None]
            wave = []
            while self._queue and len(wave) < len(free):
                wave.append(self._queue.popleft())
            by_bucket: dict = collections.OrderedDict()
            for item in wave:
                _rid, prompt, _budget = item
                bucket = next(b for b in self._buckets if b >= prompt.size)
                by_bucket.setdefault(bucket, []).append(item)
            taken = 0
            for bucket, group in by_bucket.items():
                n = len(group)
                rows = free[taken:taken + n]
                taken += n
                rp = _pad_wave(n, self._b)
                prompts = np.full((rp, bucket), self._pad, np.int32)
                last = np.zeros(rp, np.int32)
                plens = np.zeros(rp, np.int32)
                rows_pad = np.asarray(
                    rows + [rows[0]] * (rp - n), np.int32
                )
                for i in range(rp):
                    # wave padding repeats row 0's request verbatim: the
                    # duplicate prefill K/V is bit-identical (prefill is
                    # row-independent and deterministic), so the duplicate
                    # cache-scatter writes never race on ordering
                    _rid, prompt, _budget = group[i if i < n else 0]
                    prompts[i, :prompt.size] = prompt
                    last[i] = prompt.size - 1
                    plens[i] = prompt.size
                with span("serving/prefill"):
                    toks = self._prefill_wave(prompts, last, rows_pad,
                                              plens, n)
                # admission waves in the flight ring: one event per wave
                # (not per request), enough to reconstruct the admit/queue
                # rhythm in a serving post-mortem
                from tfde_tpu.observability import flightrec

                flightrec.record("admit", rows=n, bucket=int(bucket),
                                 queue_depth=len(self._queue))
                now = time.perf_counter()
                for i, (rid, prompt, budget) in enumerate(group):
                    r = rows[i]
                    self._req[r] = rid
                    self._out[r] = []
                    self._budget[r] = budget
                    self._committed[r] = prompt.size
                    t0 = self._submitted_at.pop(rid, None)
                    if t0 is not None:
                        reg.histogram("serving/ttft_ms").observe(
                            (now - t0) * 1e3
                        )
                    finished.extend(self._take_token(r, int(toks[i])))
            self._mark_dirty()
        return finished

    def _mark_dirty(self) -> None:
        """Admission invalidated the device-resident loop state (if the
        subclass keeps any)."""


class ContinuousBatcher(_BatcherBase):
    """Fixed-batch continuous serving loop over a causal LM.

    model/params: a decode-capable model (GPT family) and its params.
    batch_size: resident decode rows. max_len: per-row cache budget
    (prompt + generated must fit). scan_depth: ceiling K on fused decode
    ticks per host round-trip (see the module docstring; 1 restores the
    one-tick-per-step behavior). The sampling config is fixed per
    batcher, as for `generate`.

    Usage::

        srv = ContinuousBatcher(model, params, batch_size=4, max_len=256)
        rid = srv.submit(prompt_1d, max_new_tokens=64)
        while not srv.idle:
            for req_id, tokens in srv.step():
                ...   # finished requests, completion order

    `step()` admits queued requests into free rows (bucketed wave
    prefill) and runs ONE fused decode scan of up to `scan_depth` ticks;
    it returns the requests finishing on that call. `run()` drains
    everything.
    """

    _metrics_prefix = "serving/batcher"

    def __init__(
        self,
        model,
        params,
        batch_size: int,
        max_len: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: float = 1.0,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        rng: Optional[jax.Array] = None,
        prompt_buckets: Optional[tuple] = None,
        scan_depth: int = 4,
    ):
        if repetition_penalty <= 0.0:
            raise ValueError(
                f"repetition_penalty must be > 0 (1.0 = off), got "
                f"{repetition_penalty}"
            )
        if scan_depth < 1:
            raise ValueError(f"scan_depth must be >= 1, got {scan_depth}")
        super().__init__(model, params, batch_size, max_len, eos_id,
                         pad_id, rng, prompt_buckets)
        self._decode_model = _decode_clone(model)
        self._sampling = dict(
            temperature=float(temperature),
            top_k=top_k, top_p=top_p, min_p=min_p,
            repetition_penalty=float(repetition_penalty),
        )
        self._scan_depth = int(scan_depth)
        # presence mask for the repetition penalty (per row, prompt ids
        # included — the generate() convention); lives ON DEVICE and is
        # threaded through the fused scan, so steady-state ticks ship no
        # [B, vocab] host copies and no host-driven scatters
        self._seen = (
            jnp.zeros((batch_size, model.vocab_size), bool)
            if repetition_penalty != 1.0 else None
        )
        self._vocab = model.vocab_size

        # index leaves become [B] vectors ONCE, so the scan carry shape is
        # stable from the first tick (the per-row decode-attention branch)
        self._cache = _set_index_counters(
            init_cache(model, batch_size, self._max_len),
            np.zeros(batch_size, np.int32),
        )
        # zero row-cache templates per admission wave size, built lazily:
        # _prefill_rows does not donate its cache argument, so each
        # template survives reuse
        self._row_templates: dict = {}
        # device-resident loop state (tok/idx/budget/done); rebuilt from
        # host bookkeeping whenever admission desyncs it
        self._dev = None

    # -- public -------------------------------------------------------------
    def stats(self) -> dict:
        """Serving throughput and host-overhead accounting: decode ticks
        run, tokens delivered, tokens/round (mean occupied rows per
        tick), and the per-token host cost — jitted dispatches and
        blocking syncs per generated token (the O(1/K) bound the fused
        scan exists for; tests/test_server.py guards it)."""
        g = max(self._generated, 1)
        return {
            "rounds": self._rounds,
            "generated": self._generated,
            "tokens_per_round": self._generated / max(self._rounds, 1),
            "dispatches": self._dispatches,
            "syncs": self._syncs,
            "dispatches_per_token": self._dispatches / g,
            "syncs_per_token": self._syncs / g,
        }

    def step(self) -> list:
        """Admit into free rows, run one fused decode scan (up to
        `scan_depth` ticks); returns [(request_id, tokens 1-D np.int32),
        ...] that finished now."""
        with span("serving/admit"):
            finished = self._admit()
        active = [r for r in range(self._b) if self._req[r] is not None]
        if not active:
            self._publish_stats()
            return finished

        depth = self._pick_depth(active)
        t0 = time.perf_counter()
        with span("serving/decode"):
            if self._dev is None:
                self._upload_state()
            tok, idx, budget, done = self._dev
            rng = self._rng if self._sampling["temperature"] != 0.0 else None
            out = _decode_scan(
                self._decode_model, self._cache, self._params, tok, idx,
                budget, done, self._seen, rng, depth=depth,
                eos_id=self._eos, pad_id=self._pad, **self._sampling,
            )
            self._dispatches += 1
            (self._cache, tok, idx, budget, done, self._seen, rng,
             toks, emitted) = out
            self._dev = (tok, idx, budget, done)
            if rng is not None:
                self._rng = rng
            toks_np, emitted_np = _fetch((toks, emitted))
            self._syncs += 1
        self._rounds += depth
        n_emitted = 0
        for r in active:
            row = toks_np[r][emitted_np[r]]
            if row.size == 0:
                continue
            n_emitted += int(row.size)
            # feeding each pending token committed it; the row's last
            # sample stays pending
            self._committed[r] += int(row.size)
            for t in row:
                finished.extend(self._take_token(r, int(t)))
        if n_emitted:
            metrics.default_registry().histogram(
                "serving/ms_per_token"
            ).observe((time.perf_counter() - t0) * 1e3 / n_emitted)
        self._publish_stats()
        return finished

    # -- internals ----------------------------------------------------------
    def _validate_submit(self, prompt, max_new_tokens) -> None:
        if self._seen is not None and (
                prompt.min() < 0 or prompt.max() >= self._vocab):
            # queue-time, not admission-time (the _normalize_buckets rule):
            # jnp .at scatters DROP out-of-bounds updates silently, so an
            # over-vocab id would simply go un-penalized and a negative id
            # would mark the wrong entry via wraparound — no crash, just
            # quietly wrong sampling; refuse here instead
            raise ValueError(
                f"prompt ids must lie in [0, {self._vocab}) when "
                f"repetition_penalty is on; got "
                f"[{int(prompt.min())}, {int(prompt.max())}]"
            )
        super()._validate_submit(prompt, max_new_tokens)

    def _pick_depth(self, active) -> int:
        """K for this scan. Queue waiting: bound by the SOONEST possible
        row completion so admission latency never exceeds one short scan.
        Queue empty: bound by the LONGEST remaining budget so the
        draining tail runs no dead ticks. (EOS completions are not
        host-predictable; a mid-scan EOS freezes the row on device and
        wastes at most K-1 of its ticks.)"""
        if self._scan_depth == 1:
            return 1
        remaining = [int(self._budget[r]) for r in active]
        bound = min(remaining) if self._queue else max(remaining)
        return _ladder_depth(self._scan_depth, bound)

    def _mark_dirty(self) -> None:
        self._dev = None

    def _upload_state(self) -> None:
        """Rebuild the device loop state from host bookkeeping (after
        admission; steady state reuses the scan's own carry outputs)."""
        self._dev = (
            jnp.asarray(self._tok, jnp.int32),
            jnp.asarray(self._committed, jnp.int32),
            jnp.asarray(self._budget, jnp.int32),
            jnp.asarray(np.asarray([r is None for r in self._req])),
        )
        self._dispatches += 1  # the four small host->device transfers

    def _row_template(self, rp: int):
        if rp not in self._row_templates:
            self._row_templates[rp] = init_cache(self._model, rp,
                                                 self._max_len)
        return self._row_templates[rp]

    def _prefill_wave(self, prompts, last, rows, plens, n) -> np.ndarray:
        rp, bucket = prompts.shape
        valid = None
        if self._seen is not None:
            valid = jnp.asarray(
                np.arange(bucket)[None, :] < plens[:, None]
            )
        rng = None
        if self._sampling["temperature"] != 0.0:
            self._rng, rng = jax.random.split(self._rng)
        row_cache, tok, row_seen = _prefill_rows(
            self._decode_model, self._row_template(rp), self._params,
            jnp.asarray(prompts), jnp.asarray(last), valid, rng,
            **self._sampling,
        )
        self._dispatches += 1
        rows_dev = jnp.asarray(rows)
        self._cache = _scatter_rows(self._cache, row_cache, rows_dev)
        self._dispatches += 1
        if row_seen is not None:
            if rp > n:
                # a ladder-padding row's K/V duplicates row 0 bit-exactly,
                # but its sampled-first-token seen bit can differ under
                # temperature>0 (independent categorical draw per row) —
                # gather duplicates back to row 0's seen so the duplicate
                # scatter indices below write identical values
                sel = np.arange(rp)
                sel[n:] = 0
                row_seen = row_seen[jnp.asarray(sel)]
            self._seen = self._seen.at[rows_dev].set(row_seen)
            self._dispatches += 1
        tok_np = _fetch(tok)
        self._syncs += 1
        return tok_np


class SpeculativeContinuousBatcher(_BatcherBase):
    """Continuous batching accelerated by a draft model — the two serving
    levers composed: every round, the draft proposes `num_draft` tokens
    per row and ONE target forward verifies all of them
    (inference/speculative.py's batch-generic round, per-row acceptance),
    while finished rows admit queued requests mid-flight exactly like
    `ContinuousBatcher` — including the bucketed wave admission: both
    caches prefill every freed row of a bucket in one call each and land
    with one multi-row scatter per cache.

    temperature == 0 (default): deterministic rounds — each request's
    output equals its solo greedy `generate(model, params, prompt)` run.
    temperature > 0: speculative SAMPLING rounds (the Leviathan
    acceptance, inference/speculative.py) — committed tokens are
    distributed exactly as target-model sampling at that temperature per
    request, with draw values batch-dependent (rows share the key
    stream). Per-round commits vary between 1 and num_draft+1 tokens per
    row with draft quality; `stats()` reports the realized tokens/round
    and draft acceptance rate.
    """

    _metrics_prefix = "serving/speculative"

    def __init__(
        self,
        model,
        draft_model,
        params,
        draft_params,
        batch_size: int,
        max_len: int,
        num_draft: int = 4,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        rng: Optional[jax.Array] = None,
        prompt_buckets: Optional[tuple] = None,
    ):
        if num_draft < 1:
            raise ValueError(f"num_draft must be >= 1, got {num_draft}")
        super().__init__(model, params, batch_size, max_len, eos_id,
                         pad_id, rng, prompt_buckets)
        from tfde_tpu.inference.speculative import (
            _spec_round,
            _spec_round_sampled,
        )

        self._round = _spec_round
        self._round_sampled = _spec_round_sampled
        self._temperature = float(temperature)
        self._draft = draft_model
        self._tgt = _decode_clone(model)
        self._drf = _decode_clone(draft_model)
        self._dparams = draft_params
        self._nd = int(num_draft)
        # the speculative cache invariant: each round feeds at most
        # num_draft+1 tokens past a row's committed count before the
        # rewind (inference/speculative.py cache sizing)
        self._cache_len = self._max_len + self._nd + 1
        self._tgt_cache = init_cache(model, batch_size, self._cache_len)
        self._drf_cache = init_cache(draft_model, batch_size,
                                     self._cache_len)
        self._tgt_templates: dict = {}
        self._drf_templates: dict = {}
        self._round_tokens = 0   # tokens produced by speculative rounds
        self._draft_proposed = 0  # num_draft per active row per round
        self._draft_accepted = 0  # committed beyond the guaranteed token

    def stats(self) -> dict:
        """Speculation effectiveness: tokens/round is per ROW per round
        (1.0 = no draft ever accepted, num_draft+1 = perfect draft);
        acceptance_rate is the fraction of proposed draft tokens the
        target committed. dispatches/syncs mirror ContinuousBatcher's
        host-overhead accounting."""
        return {
            "rounds": self._rounds,
            "generated": self._generated,
            "tokens_per_round": (
                self._round_tokens / max(self._rounds * self._b, 1)
            ),
            "acceptance_rate": (
                self._draft_accepted / max(self._draft_proposed, 1)
            ),
            "dispatches": self._dispatches,
            "syncs": self._syncs,
        }

    def _validate_submit(self, prompt, max_new_tokens) -> None:
        super()._validate_submit(prompt, max_new_tokens)
        validate_budget(self._draft, int(prompt.size), max_new_tokens)

    def _template(self, cache_dict, model, rp: int):
        if rp not in cache_dict:
            cache_dict[rp] = init_cache(model, rp, self._cache_len)
        return cache_dict[rp]

    def _prefill_wave(self, prompts, last, rows, plens, n) -> np.ndarray:
        rp = prompts.shape[0]
        prompts_dev = jnp.asarray(prompts)
        last_dev = jnp.asarray(last)
        rng = None
        if self._temperature > 0.0:
            self._rng, rng = jax.random.split(self._rng)
        tgt_rows, tok, _ = _prefill_rows(
            self._tgt, self._template(self._tgt_templates, self._model, rp),
            self._params, prompts_dev, last_dev, None, rng,
            temperature=self._temperature, top_k=None, top_p=None,
            min_p=None, repetition_penalty=1.0,
        )
        # the draft prefill only needs its cache filled; its sampled token
        # is discarded (greedy argmax — no rng consumed)
        drf_rows, _, _ = _prefill_rows(
            self._drf, self._template(self._drf_templates, self._draft, rp),
            self._dparams, prompts_dev, last_dev, None, None,
            temperature=0.0, top_k=None, top_p=None, min_p=None,
            repetition_penalty=1.0,
        )
        self._dispatches += 2
        rows_dev = jnp.asarray(rows)
        self._tgt_cache = _scatter_rows(self._tgt_cache, tgt_rows, rows_dev)
        self._drf_cache = _scatter_rows(self._drf_cache, drf_rows, rows_dev)
        self._dispatches += 2
        tok_np = _fetch(tok)
        self._syncs += 1
        return tok_np

    def step(self) -> list:
        """Admit, then run ONE speculative round for the whole batch;
        returns the requests that finished on it."""
        with span("serving/admit"):
            finished = self._admit()
        active = [r for r in range(self._b) if self._req[r] is not None]
        if not active:
            self._publish_stats()
            return finished
        self._rounds += 1
        t0 = time.perf_counter()
        with span("serving/decode"):
            # per-round rewind is unconditional: acceptance lengths diverge
            # every round (host ints/np arrays — own buffer per index leaf,
            # across BOTH donated caches)
            committed = self._committed.astype(np.int32)
            self._tgt_cache = _set_index_counters(self._tgt_cache, committed)
            self._drf_cache = _set_index_counters(self._drf_cache, committed)
            self._dispatches += 2
            if self._temperature > 0.0:
                self._rng, sub = jax.random.split(self._rng)
                (self._tgt_cache, self._drf_cache, round_toks, n_new,
                 _pending, _rng_out) = self._round_sampled(
                    self._tgt, self._drf, self._tgt_cache, self._drf_cache,
                    self._params, self._dparams,
                    jnp.asarray(self._tok, jnp.int32), sub, self._nd,
                    self._pad, self._temperature,
                )
            else:
                (self._tgt_cache, self._drf_cache, round_toks, n_new,
                 _pending) = self._round(
                    self._tgt, self._drf, self._tgt_cache, self._drf_cache,
                    self._params, self._dparams,
                    jnp.asarray(self._tok, jnp.int32), self._nd, self._pad,
                )
            self._dispatches += 1
            round_np, n_np = _fetch((round_toks, n_new))
            self._syncs += 1
        n_emitted = 0
        for r in active:
            toks = round_np[r, : int(n_np[r])].tolist()
            taken = 0
            for t in toks:
                if self._req[r] is None:
                    break  # row finished mid-round; overshoot discarded
                self._round_tokens += 1
                finished.extend(self._take_token(r, int(t)))
                taken += 1
            n_emitted += taken
            # acceptance bookkeeping: each round proposes num_draft per
            # active row; a row's commits beyond the guaranteed target
            # token are accepted draft proposals (capped by num_draft —
            # the +1'th commit is the bonus token, not a draft)
            self._draft_proposed += self._nd
            self._draft_accepted += min(max(taken - 1, 0), self._nd)
            if self._req[r] is not None:
                # row still active: tok_last + accepted tokens are now in
                # both caches (the pending one stays unfed) — the
                # generate_speculative commit bookkeeping
                self._committed[r] += taken
        if n_emitted:
            metrics.default_registry().histogram(
                "serving/ms_per_token"
            ).observe((time.perf_counter() - t0) * 1e3 / n_emitted)
        self._publish_stats()
        return finished
