"""Continuous batching — the serving loop that keeps every batch row busy.

`generate` (inference/decode.py) serves one batch to completion: rows that
finish early ride along as padding until the slowest row ends, and new
requests wait for the whole batch. A serving deployment wants the modern
alternative: a FIXED decode batch where a finished row is immediately
re-used for the next queued request while the other rows keep decoding —
continuous batching (the vLLM/Orca scheduling idea, re-built on this
framework's primitives).

What makes it cheap here: the per-row KV-cache machinery built for
batched speculative decoding (models/transformer.py `_decode_attention`
vector branch + per-row `position_index`) already lets every batch row
sit at a DIFFERENT sequence position with its own validity horizon.
Admission is then per-row cache surgery:

- one compiled DECODE SCAN serves the whole batch for K ticks: the model
  forward, the sampler (temperature/top-k/top-p/min-p/repetition
  penalty, `seen`-mask update included), per-row EOS/budget masking and
  index bookkeeping all live inside ONE jitted `lax.scan`, so the host
  pays one dispatch and one sync per K tokens per row instead of three
  or more per token (the 97x serve-vs-decode gap BENCH_r05 measured was
  exactly this host overhead);
- finished rows freeze mid-scan: they feed `pad_id`, their index stops
  advancing, and their sampled output is masked — on-device, no host
  round-trip (a frozen row's final pad writes land beyond its committed
  count and stay unreachable, the stale-K/V invariant);
- one compiled PREFILL per distinct prompt BUCKET admits every freed row
  of that bucket at once ([R, Pbucket] prompts, first tokens sampled
  inside the same program), and one multi-row cache scatter lands all of
  them (`.at[rows].set`) — admission cost amortizes over the wave
  instead of paying a prefill + scatter round-trip per row;
- EOS, budget, and queue bookkeeping are per-row host state, replayed
  from the scan's [B, K] token/emitted output after the single fetch.

Greedy determinism: each request's output equals a solo
`generate(model, params, prompt)` run token for token regardless of what
shares the batch or the scan depth K (rows are independent through
attention's per-row validity masks; tests/test_server.py asserts it
across staggered admissions and scan depths). Temperature>0 draws ride a
shared key stream — distributionally correct per request, draw values
batch-dependent.

Scan-depth adaptation: `scan_depth` is the K ceiling. When the queue is
non-empty K drops toward the soonest row completion (host-known budget;
EOS is not host-predictable) so a freed row admits without waiting out a
long scan; when the queue is empty K is capped by the longest remaining
budget so a draining batch never runs dead ticks. K is chosen from the
power-of-two ladder {1, 2, 4, ..., scan_depth} to bound compile count at
O(log scan_depth).

Prompt-length compiles: prompts are right-padded to the smallest of
`prompt_buckets` that fits (powers of two up to max_len by default), so
the prefill compiles once per BUCKET (x the power-of-two wave-size
ladder), not per length — the first-token logits are read at each row's
true last position, and the admission-time index rewind makes the pad
K/V unreachable.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import itertools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu import knobs
from tfde_tpu.inference import admission as _admission
from tfde_tpu.inference import paged as _paged
from tfde_tpu.inference.decode import (
    _decode_clone,
    init_cache,
    sample_logits,
    validate_budget,
)
from tfde_tpu.inference.prefix_cache import (
    DEFAULT_BLOCK,
    is_index_leaf,
    leaf_name,
    resolve as _resolve_prefix,
)
from tfde_tpu.inference.speculative import _set_index_counters
from tfde_tpu.analysis import hlolint as _hlolint
from tfde_tpu.observability import boot as _boot
from tfde_tpu.observability import capacity as _capacity
from tfde_tpu.observability import memwatch as _memwatch
from tfde_tpu.observability import metrics
from tfde_tpu.observability import recompile as _recompile
from tfde_tpu.observability import trace as _trace
from tfde_tpu.observability.spans import span

#: per-batcher fingerprint tag: distinct batcher instances hold distinct
#: static model objects, so the SAME (kind, key, wave) signature compiles
#: separately per instance — the recompile sentinel's fingerprints carry
#: this tag so a second batcher's first wave reads as a novel compile,
#: not as an unexpected recompile of the first batcher's site
_BATCHER_TAGS = itertools.count()


def _fetch(tree):
    """THE host sync: one blocking device->host fetch for everything the
    host loop needs this round. Kept as a module-level seam so tests can
    count syncs (tests/test_server.py's dispatch-budget regression guard)
    and so no call site is tempted to sprinkle per-array np.asarray
    fetches back onto the hot path."""
    return jax.device_get(tree)


@functools.partial(
    jax.jit,
    static_argnames=("model", "depth", "temperature", "top_k", "top_p",
                     "min_p", "repetition_penalty", "eos_id", "pad_id"),
    donate_argnums=(1, 3, 4, 5, 6, 7),
)
def _decode_scan(model, cache, params, tok, idx, budget, done, seen, rng,
                 depth, temperature, top_k, top_p, min_p,
                 repetition_penalty, eos_id, pad_id):
    """K = `depth` fused decode ticks for the whole batch, device-resident.

    Carry per row r: `tok[r]` the pending (sampled, unfed) token, `idx[r]`
    the committed token count (cache index), `budget[r]` remaining output
    tokens, `done[r]` frozen flag, plus the optional [B, V] `seen`
    presence mask and the sampling key. Each tick feeds the pending
    token, samples the next one with the FULL sampling config in-program
    (no separate sample_logits dispatch, no host `.at[]` seen update),
    and applies EOS/budget masking on device: a finishing row emits its
    last token, flips `done`, and thereafter feeds `pad_id` with a frozen
    index (its pad K/V lands beyond the committed count — unreachable).

    Returns (cache, tok, idx, budget, done, seen, rng, toks [B, K],
    emitted [B, K]): `toks[r]` masked to `pad_id` where not emitted;
    `emitted[r]` is a True-prefix per row (rows freeze monotonically), so
    the host replays exactly `emitted[r].sum()` tokens into its
    bookkeeping after the ONE fetch.

    The greedy path (temperature == 0.0) carries `rng=None` and performs
    no `jax.random.split` at all — dead device work the per-tick loop
    used to pay on every step.
    """

    def body(carry, _):
        cache, tok, idx, budget, done, seen, rng = carry
        # index surgery each tick instead of trusting the model's own
        # advance: frozen rows must NOT advance, and writing the [B]
        # vector here keeps the carry shape stable from tick one
        cache = _set_index_counters(cache, idx)
        feed = jnp.where(done, jnp.int32(pad_id), tok)
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, feed[:, None], train=False,
            mutable=["cache"],
        )
        cache = mutated["cache"]
        logits = logits[:, -1].astype(jnp.float32)
        if temperature != 0.0:
            rng, sub = jax.random.split(rng)
        else:
            sub = rng  # greedy: sample_logits is argmax, rng untouched
        nxt = sample_logits(
            logits, sub, temperature=temperature, top_k=top_k, top_p=top_p,
            min_p=min_p, repetition_penalty=repetition_penalty, seen=seen,
        )
        live = ~done
        nxt = jnp.where(done, jnp.int32(pad_id), nxt)
        if seen is not None:
            ar = jnp.arange(nxt.shape[0])
            seen = jnp.where(done[:, None], seen,
                             seen.at[ar, nxt].set(True))
        # feeding tok committed it; the new sample is now pending
        idx = idx + live.astype(jnp.int32)
        budget = budget - live.astype(jnp.int32)
        fin = budget <= 0
        if eos_id is not None:
            fin = fin | (nxt == eos_id)
        done = done | (live & fin)
        tok = jnp.where(live, nxt, tok)
        return (cache, tok, idx, budget, done, seen, rng), (nxt, live)

    carry = (cache, tok, idx, budget, done, seen, rng)
    carry, (toks, emitted) = jax.lax.scan(body, carry, length=depth)
    cache, tok, idx, budget, done, seen, rng = carry
    return (cache, tok, idx, budget, done, seen, rng,
            jnp.moveaxis(toks, 0, 1), jnp.moveaxis(emitted, 0, 1))


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "top_k", "top_p", "min_p",
                     "repetition_penalty"),
    donate_argnums=(1,),
)
def _prefill_rows(model, row_cache, params, prompts, last, valid, rng,
                  temperature, top_k, top_p, min_p, repetition_penalty):
    """Prefill R rows of one bucket in ONE call and sample each row's
    first token inside the same program.

    prompts: [R, Pbucket] right-padded prompt batch; `last` [R] the true
    last position per row (so bucketing never changes the first sampled
    token); `valid` [R, Pbucket] marks real (non-pad) prompt positions —
    only consulted when the repetition penalty is on, where it keeps pad
    slots out of the presence mask. Compiled per (bucket length, wave
    size); the admission ladder pads the wave to a power of two by
    REPEATING a real row (identical content, so the duplicate scatter
    writes are idempotent) to bound compile count.

    `row_cache` is DONATED: the mutated cache aliases the input buffers
    instead of paying a device-side copy of every K/V leaf per admission
    wave (tests/test_server.py pins the aliasing in the lowered HLO), so
    callers must hand in a FRESH zero tree each wave — `_row_template`
    materializes one from cached shapes.

    Returns (filled row cache, first tokens [R], seen rows [R, V] or
    None). Pad correctness rides the per-row index machinery: pad K/V
    lands beyond each row's committed count once the admission rewind
    sets it to the TRUE prompt length."""
    logits, mutated = model.apply(
        {"params": params, "cache": row_cache}, prompts, train=False,
        mutable=["cache"],
    )
    r = prompts.shape[0]
    ar = jnp.arange(r)
    logits = logits[ar, last].astype(jnp.float32)
    row_seen = None
    if repetition_penalty != 1.0:
        hits = jnp.zeros((r, model.vocab_size), jnp.int32)
        hits = hits.at[ar[:, None], prompts].add(valid.astype(jnp.int32))
        row_seen = hits > 0
    tok = sample_logits(
        logits, rng, temperature=temperature, top_k=top_k, top_p=top_p,
        min_p=min_p, repetition_penalty=repetition_penalty, seen=row_seen,
    )
    if row_seen is not None:
        row_seen = row_seen.at[ar, tok].set(True)
    return mutated["cache"], tok, row_seen


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(cache, rows_cache, rows):
    """Write an R-row prefill cache's K/V leaves into batch rows `rows`
    ([R] int32) in ONE donated update — the multi-row generalization of
    the old per-row `.at[row].set` round-trip. Index counters pass
    through (the decode scan rewrites them from the host's committed
    counts every tick). Wave padding duplicates a real row verbatim, so
    duplicate indices in `rows` write identical values and the scatter
    stays deterministic."""

    def merge(path, big, small):
        name = str(getattr(path[-1], "key", path[-1]))
        if name in ("cache_index", "position_index"):
            return big
        return big.at[rows].set(small.astype(big.dtype))

    return jax.tree_util.tree_map_with_path(merge, cache, rows_cache)


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "top_k", "top_p", "min_p",
                     "repetition_penalty"),
    donate_argnums=(1,),
)
def _prefill_suffix(model, row_cache, params, prefix_kv, suffixes, last,
                    fullp, valid, rng, temperature, top_k, top_p, min_p,
                    repetition_penalty):
    """Warm admission: land a cached prefix and prefill only the suffix,
    in ONE program.

    prefix_kv: {leaf-name: [R, L, ...]} — L cached prefix tokens of K/V
    per row (prefix_cache.py trie segments, stacked per wave). They are
    written at positions [:L], the index counters are set to L (the
    speculative-decoding arbitrary-start contract), and the model then
    consumes `suffixes` [R, Sbucket] as a normal feed starting at
    position L — bit-identical to having prefilled the whole prompt
    (tests/test_prefix_cache.py pins it). `last` [R] is the suffix-local
    last position; `fullp`/`valid` [R, Fbucket] carry the FULL padded
    prompt for the repetition-penalty presence mask (None when the
    penalty is off). `row_cache` is donated, as in `_prefill_rows`.

    Returns (filled row cache, first tokens [R], seen rows or None)."""
    some = next(iter(prefix_kv.values()))
    pre_len = some.shape[1]

    def put(path, big):
        if is_index_leaf(path):
            return big
        seg = prefix_kv[leaf_name(path)]
        return big.at[:, :pre_len].set(seg.astype(big.dtype))

    row_cache = jax.tree_util.tree_map_with_path(put, row_cache)
    row_cache = _set_index_counters(row_cache, jnp.int32(pre_len))
    logits, mutated = model.apply(
        {"params": params, "cache": row_cache}, suffixes, train=False,
        mutable=["cache"],
    )
    r = suffixes.shape[0]
    ar = jnp.arange(r)
    logits = logits[ar, last].astype(jnp.float32)
    row_seen = None
    if repetition_penalty != 1.0:
        hits = jnp.zeros((r, model.vocab_size), jnp.int32)
        hits = hits.at[ar[:, None], fullp].add(valid.astype(jnp.int32))
        row_seen = hits > 0
    tok = sample_logits(
        logits, rng, temperature=temperature, top_k=top_k, top_p=top_p,
        min_p=min_p, repetition_penalty=repetition_penalty, seen=row_seen,
    )
    if row_seen is not None:
        row_seen = row_seen.at[ar, tok].set(True)
    return mutated["cache"], tok, row_seen


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_primed_rows(cache, kv, rows):
    """Land primed rows — prompts whose prefill ran on ANOTHER replica
    (the prefill/decode role split) — into batch rows `rows` in one
    donated update. kv: {leaf-name: [R, Pbucket, ...]} right-padded
    primed K/V; positions past each row's true prompt length carry
    zeros, which land beyond the committed count and stay unreachable
    (the stale-K/V invariant). Index counters pass through, exactly as
    in `_scatter_rows`."""

    def merge(path, big):
        if is_index_leaf(path):
            return big
        seg = kv[leaf_name(path)]
        return big.at[rows, :seg.shape[1]].set(seg.astype(big.dtype))

    return jax.tree_util.tree_map_with_path(merge, cache)


@functools.partial(jax.jit, static_argnames=("model",), donate_argnums=(1,))
def _paged_prefill_chunk(model, cache, params, tokens, idx, take, last_in,
                         prev):
    """ONE chunk of paged prefill over the FULL batch — the pad-ladder
    compile collapse.

    The dense path compiles a prefill per (prompt bucket, wave width)
    cell; under paging the writes scatter through each row's block
    table, so admission instead feeds prompts through this single
    [B, C] program chunk-by-chunk: `tokens` carries chunk j of each
    admitting row's suffix (pad elsewhere), `idx` [B] the chunk's start
    position per row — an admitting row's `pre_len + j*C`, an exhausted
    or non-wave row's committed count. Any shape of (prompt length,
    admitting rows) is just a different DATA pattern, so the program
    compiles ONCE per batcher (tests/test_paged.py pins it).

    Junk discipline: rows not writing real tokens this chunk still
    write C pad K/V cells, all beyond their committed count — into
    their own allocated-uncommitted cells (overwritten position-exactly
    before any validity mask reaches them) or the null block. `take`
    marks rows whose TRUE last prompt position falls in this chunk (at
    chunk-local `last_in`); their final-position logits replace their
    slot in the `prev` [B, V] carry, so after the last chunk every
    admitting row's first-token logits are in hand without per-bucket
    gather programs. Donates `cache` like every prefill."""
    cache = _set_index_counters(cache, idx)
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, tokens, train=False,
        mutable=["cache"],
    )
    ar = jnp.arange(tokens.shape[0])
    out = jnp.where(take[:, None],
                    logits[ar, last_in].astype(jnp.float32), prev)
    return mutated["cache"], out


@functools.partial(
    jax.jit,
    static_argnames=("temperature", "top_k", "top_p", "min_p",
                     "repetition_penalty"),
)
def _sample_first(logits, rng, seen, temperature, top_k, top_p, min_p,
                  repetition_penalty):
    """First-token sampling for a paged admission wave: the chunk loop
    above hands back last-position logits; this samples them with the
    full config (presence mask included — `seen` rows are rebuilt host-
    side from prompt ids, the primed-wave idiom). Compiled per padded
    wave width on the usual ladder; tiny (no cache, no model)."""
    tok = sample_logits(
        logits, rng, temperature=temperature, top_k=top_k, top_p=top_p,
        min_p=min_p, repetition_penalty=repetition_penalty, seen=seen,
    )
    if seen is not None:
        seen = seen.at[jnp.arange(tok.shape[0]), tok].set(True)
    return tok, seen


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_primed_blocks(cache, kv, blk):
    """Paged twin of `_scatter_primed_rows`: land shipped host K/V
    (re-chunked to [R, NB, block, ...], dense leaf names) into the pool
    blocks `blk` [R, NB] in one donated update. Slots past a row's
    prompt blocks carry the null block and zero payload — identical-
    value duplicate writes, so scatter order never matters. Block
    tables and index counters pass through (the host uploaded tables
    already)."""

    def merge(path, big):
        name = str(getattr(path[-1], "key", path[-1]))
        if is_index_leaf(path) or name == "block_table":
            return big
        seg = kv[_paged.pool_leaf_name(leaf_name(path))]
        return big.at[blk].set(seg.astype(big.dtype))

    return jax.tree_util.tree_map_with_path(merge, cache)


@dataclasses.dataclass
class PrimedRequest:
    """A prefill-role replica's hand-off unit: everything a decode
    replica needs to admit the request without running the prompt
    forward itself. `kv` holds HOST arrays ({leaf-name: [P, ...]}), so
    the object is process-portable — inference/router.py ships it as
    JSON between replica processes. Greedy decoding of a primed request
    is bit-identical to a locally-admitted one; at temperature > 0 the
    first token was drawn from the PREFILL replica's key stream."""

    prompt: np.ndarray          # [P] int32 token ids
    first_token: int            # sampled at prefill time (pending, unfed)
    max_new_tokens: int
    kv: dict                    # leaf-name -> np.ndarray [P, ...]


def _normalize_buckets(buckets, max_len: int) -> tuple:
    """Sorted prefill bucket lengths; default powers of two up to
    max_len. Every prompt pads up to the smallest bucket that fits."""
    if buckets is None:
        buckets, b = [], 8
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
    # clamp to max_len: a larger bucket would pad past the row cache and
    # fail at ADMISSION (after the request left the queue), not here
    out = tuple(sorted({min(int(b), max_len) for b in buckets}))
    if not out or out[-1] < max_len:
        raise ValueError(
            f"prompt_buckets must cover max_len {max_len}; got {out}"
        )
    return out


def _bucketed(prompt: np.ndarray, buckets: tuple, pad_id: int):
    """(padded [1, bucket] int32 prompt, true-last-position index)."""
    p = prompt.size
    bucket = next(b for b in buckets if b >= p)
    padded = np.full((1, bucket), pad_id, np.int32)
    padded[0, :p] = prompt
    return jnp.asarray(padded), p - 1


def _ladder_depth(cap: int, bound: int) -> int:
    """Scan depth for this round: the largest value from the ladder
    {1, 2, 4, ..., cap} (cap always included) that is <= bound. Host
    bookkeeping picks `bound` from remaining budgets, so compiles stay
    O(log cap) while K still shrinks to 1 near a row completion."""
    bound = min(cap, max(1, bound))
    if bound >= cap:
        return cap
    k = 1
    while k * 2 <= bound:
        k *= 2
    return k


class _PriorityDeque:
    """The batcher's request queue: one FIFO lane per priority class,
    drained highest-priority-first (`interactive` > `batch` >
    `best_effort`, FIFO within a class). Presents the deque surface the
    admission/accounting code already speaks — truthiness, `len`,
    iteration (in drain order), `popleft` — so single-class traffic
    behaves exactly like the plain deque it replaces."""

    def __init__(self):
        self._lanes = collections.OrderedDict(
            (p, collections.deque()) for p in _admission.PRIORITIES
        )

    def append(self, item,
               priority: str = _admission.DEFAULT_PRIORITY) -> None:
        self._lanes[priority].append(item)

    def appendleft(self, item,
                   priority: str = _admission.DEFAULT_PRIORITY) -> None:
        """Put a dequeued item BACK at the front of its lane — the
        capacity-gate requeue (the item keeps its FIFO slot; nothing
        behind it in the lane overtakes it)."""
        self._lanes[priority].appendleft(item)

    def popleft(self):
        for lane in self._lanes.values():
            if lane:
                return lane.popleft()
        raise IndexError("pop from an empty priority queue")

    def remove_rid(self, rid: int) -> bool:
        """Drop the queued item with request id `rid` (cancel path)."""
        for lane in self._lanes.values():
            for i, item in enumerate(lane):
                if item[0] == rid:
                    del lane[i]
                    return True
        return False

    def depths(self) -> dict:
        """Per-class queue depth (the /load snapshot detail)."""
        return {p: len(lane) for p, lane in self._lanes.items()}

    def __len__(self) -> int:
        return sum(len(lane) for lane in self._lanes.values())

    def __bool__(self) -> bool:
        return any(self._lanes.values())

    def __iter__(self):
        for lane in self._lanes.values():
            yield from lane


def _pad_wave(r: int, cap: int) -> int:
    """Admission wave sizes ride their own power-of-two ladder (capped at
    the batch size) so `_prefill_rows` compiles O(log B) per bucket, not
    O(B)."""
    k = 1
    while k < r:
        k *= 2
    return min(k, cap)


class _BatcherBase:
    """Machinery shared by `ContinuousBatcher` and
    `SpeculativeContinuousBatcher`: the request queue, per-row host
    bookkeeping (`_take_token`), batched bucket admission (`_admit`
    drives the subclass `_prefill_wave` hook), stats publication, and
    the dispatch/sync accounting the bench and the regression-guard test
    read back.

    Invariant per active row r (the speculative-decoding contract): the
    cache holds K/V for exactly `committed[r]` tokens and `tok[r]` is the
    last generated-but-unfed token.
    """

    _metrics_prefix = "serving/batcher"

    def __init__(self, model, params, batch_size: int, max_len: int,
                 eos_id, pad_id: int, rng, prompt_buckets,
                 role: str = "both", admission_ctl=None):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', got {role!r}"
            )
        self._buckets = _normalize_buckets(prompt_buckets, max_len)
        self._model = model
        self._params = params
        self._b = batch_size
        self._max_len = int(max_len)
        self._eos = eos_id
        self._pad = pad_id
        self._rng = rng if rng is not None else jax.random.key(0)
        self._role = role

        self._req = [None] * batch_size          # request id or None
        self._out = [[] for _ in range(batch_size)]
        self._budget = np.zeros(batch_size, np.int64)
        self._committed = np.zeros(batch_size, np.int64)
        self._tok = np.full(batch_size, pad_id, np.int64)
        # queue items: (rid, prompt [P] np.int64, budget, primed|None) —
        # `primed` set only for submit_primed() entries (K/V in hand).
        # Drained highest-priority-first; FIFO within a class.
        self._queue: _PriorityDeque = _PriorityDeque()
        # admission policy: caps + drain-rate estimate (defaults read
        # TFDE_ADMIT_*; everything off unless configured)
        self._admission = (admission_ctl if admission_ctl is not None
                           else _admission.AdmissionController())
        self._priority: dict = {}       # rid -> priority class
        self._deadline_at: dict = {}    # rid -> absolute TTFT deadline
        self._shed: set = set()         # rids deadline-shed at dequeue
        self._submitted_at: dict = {}   # rid -> submit wall time (TTFT)
        self._first_at: dict = {}       # rid -> first-token time (TPOT)
        # rid -> request trace id; populated ONLY while the trace ring is
        # active AND the submitter handed one over, so the off path pays
        # an empty-dict truthiness check and nothing else
        self._trace_ids: dict = {}
        self._next_id = 0
        self._rounds = 0         # decode ticks run
        self._generated = 0      # every delivered token (incl. prefill 1st)
        self._dispatches = 0     # jitted-program / eager-op invocations
        self._syncs = 0          # blocking device->host fetches
        # per-request incremental delivery (router/SSE): off by default —
        # run()/step() consumers read completions, not partials, and an
        # unread stream entry would leak
        self._track_progress = False
        self._stream: dict = {}  # rid -> {"tokens", "taken", "done"}
        # paged KV (TFDE_PAGED_KV): only ContinuousBatcher implements the
        # block-pool layout; the flag lives on the base so the shared
        # admission/step machinery can branch safely from any subclass
        self._paged = False
        # recompile-sentinel fingerprint tag + the memory-ledger program
        # names this instance already registered (one interrogation per
        # pad-ladder bucket, not per wave)
        self._rc_tag = next(_BATCHER_TAGS)
        self._mem_programs: set = set()
        # KV-capacity observability (observability/capacity.py): the
        # ledger/headroom pair is built by the subclass once its slab
        # exists (`_init_capacity`); the usage meter is per-batcher and
        # live immediately (its JSONL log arms lazily via TFDE_USAGE_LOG
        # or the owning ReplicaServer's model_dir)
        self._ledger = None
        self._cap_model = None
        self._usage = _capacity.UsageMeter()
        # serving-side bounded capture: armed via attach_profiler /
        # POST /profile, driven once per step from the decode-round hook
        self._profiler = None

    def attach_profiler(self, profiler) -> None:
        """Give this batcher a RoundWindowProfiler (observability/profiler);
        armed windows open/close on decode-round boundaries."""
        self._profiler = profiler

    def _profiler_round(self, traced) -> None:
        if self._profiler is not None:
            self._profiler.on_round(self._rounds, traces=traced or None)

    #: subclasses that implement `_primed_wave` + `prime` flip this
    _accepts_primed = False

    # -- public -------------------------------------------------------------
    @property
    def idle(self) -> bool:
        return not self._queue and all(r is None for r in self._req)

    @property
    def free_rows(self) -> int:
        return sum(r is None for r in self._req)

    @property
    def role(self) -> str:
        return self._role

    @property
    def outstanding_tokens(self) -> int:
        """Remaining output-token budget across active rows plus the
        queue — the router's least-loaded placement signal (exported as
        a serving gauge via `_publish_stats`)."""
        active = sum(
            int(self._budget[r]) for r in range(self._b)
            if self._req[r] is not None
        )
        return active + sum(int(b) for _rid, _p, b, _pr in self._queue)

    @property
    def queued_tokens(self) -> int:
        """Output-token backlog of QUEUED requests only (active rows are
        already paid for) — the admission cap's and the drain-rate
        estimate's unit."""
        return sum(int(b) for _rid, _p, b, _pr in self._queue)

    @property
    def admission(self) -> "_admission.AdmissionController":
        return self._admission

    @property
    def usage(self) -> "_capacity.UsageMeter":
        return self._usage

    def arm_usage_log(self, model_dir=None) -> None:
        """Late-bind the usage JSONL log (TFDE_USAGE_LOG=on needs a
        model_dir to anchor the file; the ReplicaServer calls this with
        its own)."""
        self._usage.arm(model_dir)

    def _init_capacity(self, cache, cells_per_row: Optional[int] = None
                       ) -> None:
        """Build the KV occupancy ledger + headroom model from the
        freshly-initialized dense slab (subclass constructors call this
        once the cache exists). `cells_per_row` defaults to max_len;
        the speculative batcher's slab carries draft slack beyond it."""
        cells = int(cells_per_row if cells_per_row is not None
                    else self._max_len)
        self._ledger = _capacity.CapacityLedger.from_cache(
            cache, self._b, cells)
        self._cap_model = _capacity.CapacityModel(self._ledger)

    def kv_stats(self) -> dict:
        """Current KV occupancy + headroom (the /load and 429 `kv`
        block); refreshes the kv/* gauges as a side effect. Empty dict
        until a subclass wired its slab."""
        if self._ledger is None:
            return {}
        s = self._ledger.observe(self._committed, self._req)
        s.update(self._cap_model.headroom(s))
        return s

    def was_shed(self, rid: int) -> bool:
        """True exactly once for a request that was deadline-shed at
        dequeue — the HTTP layer reads this to turn the empty completion
        into an explicit shed event on the stream."""
        if rid in self._shed:
            self._shed.discard(rid)
            return True
        return False

    def submit(self, prompt, max_new_tokens: int,
               trace: Optional[str] = None,
               priority: Optional[str] = None,
               ttft_deadline_ms: Optional[float] = None) -> int:
        """Queue a request; returns its id. prompt: 1-D int token ids.
        `trace`: the request's distributed-trace id (X-Tfde-Trace),
        recorded on every span event the request generates.
        `priority`: admission class ('interactive' > 'batch' >
        'best_effort'; default interactive) — the queue drains
        highest-priority-first. `ttft_deadline_ms`: shed the request at
        dequeue if its queue wait alone already blew this budget
        (default: the controller's TFDE_ADMIT_TTFT_DEADLINE_MS).
        Raises `admission.QueueFull` when a configured cap is hit."""
        if self._role == "prefill":
            raise RuntimeError(
                "prefill-only replica: use prime() and hand the result to "
                "a decode replica's submit_primed()"
            )
        prompt = self._check_request(prompt, max_new_tokens)
        pr = _admission.validate_priority(priority)
        self._admission_check(int(max_new_tokens))
        rid = self._enqueue(prompt, int(max_new_tokens), None, trace,
                            priority=pr, ttft_deadline_ms=ttft_deadline_ms)
        return rid

    def submit_primed(self, primed: PrimedRequest,
                      trace: Optional[str] = None,
                      priority: Optional[str] = None,
                      ttft_deadline_ms: Optional[float] = None) -> int:
        """Queue a request whose prefill already ran on a prefill-role
        replica (`prime()`); only the K/V scatter and decode happen
        here. Returns the local request id."""
        if not self._accepts_primed:
            raise RuntimeError(
                f"{type(self).__name__} does not accept primed requests"
            )
        if self._role == "prefill":
            raise RuntimeError("prefill-only replica cannot decode")
        prompt = self._check_request(primed.prompt, primed.max_new_tokens)
        pr = _admission.validate_priority(priority)
        self._admission_check(int(primed.max_new_tokens))
        return self._enqueue(prompt, int(primed.max_new_tokens), primed,
                             trace, priority=pr,
                             ttft_deadline_ms=ttft_deadline_ms)

    def _admission_check(self, budget: int) -> None:
        """One admission gate for both submit paths: queue caps plus —
        when a ledger is wired and TFDE_ADMIT_KV_HEADROOM set — the
        memory gate, with the kv snapshot riding any rejection and the
        outstanding decode backlog as the Retry-After basis when
        headroom (not queue depth) binds."""
        if (self._ledger is not None
                and self._admission.min_headroom_rows):
            kv = self.kv_stats()
            self._admission.check(
                len(self._queue), self.queued_tokens, budget,
                headroom_rows=kv.get("headroom_rows"), kv=kv,
                drain_tokens=self.outstanding_tokens)
        else:
            self._admission.check(len(self._queue), self.queued_tokens,
                                  budget)

    def enable_progress(self) -> None:
        """Track per-request incremental tokens for `take_progress` (the
        router's SSE feed). Applies to requests submitted after the
        call."""
        self._track_progress = True

    def take_progress(self, rid: int):
        """(new tokens since the last take, done flag) for an in-flight
        request. Requires `enable_progress()` before submit. A finished
        request's entry is dropped by the take that drains it."""
        ent = self._stream[rid]
        toks = ent["tokens"][ent["taken"]:]
        ent["taken"] += len(toks)
        if ent["done"] and ent["taken"] == len(ent["tokens"]):
            del self._stream[rid]
        return toks, ent["done"]

    def run(self) -> list:
        """Step until idle; returns every completion in finish order."""
        done = []
        while not self.idle:
            done.extend(self.step())
        return done

    def cancel(self, rid: int) -> bool:
        """Abandon a request whose consumer is gone (router client
        disconnect): drop it from the queue, or free its row so the
        decode scan stops spending ticks on it. Partial output is
        discarded. Returns whether the request was found in flight."""
        self._stream.pop(rid, None)
        self._submitted_at.pop(rid, None)
        self._first_at.pop(rid, None)
        self._priority.pop(rid, None)
        self._deadline_at.pop(rid, None)
        self._shed.discard(rid)
        tid = self._trace_ids.pop(rid, None)
        if tid is not None:
            _trace.event("serve/cancelled", trace=tid, rid=rid)
        if self._queue.remove_rid(rid):
            self._usage.finish(rid, 0, outcome="cancelled")
            return True
        for r in range(self._b):
            if self._req[r] == rid:
                self._usage.finish(rid, len(self._out[r]),
                                   outcome="cancelled")
                self._release_row(r)
                self._req[r] = None
                self._out[r] = []
                self._budget[r] = 0
                self._committed[r] = 0
                self._tok[r] = self._pad
                # the device loop state still thinks the row is live;
                # force a rebuild so its done flag flips before the next
                # scan
                self._mark_dirty()
                return True
        return False

    def _check_request(self, prompt, max_new_tokens: int) -> np.ndarray:
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens > self._max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the batcher's max_len "
                f"{self._max_len}"
            )
        self._validate_submit(prompt, max_new_tokens)
        return prompt

    def _enqueue(self, prompt: np.ndarray, budget: int, primed,
                 trace: Optional[str] = None,
                 priority: str = _admission.DEFAULT_PRIORITY,
                 ttft_deadline_ms: Optional[float] = None) -> int:
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, prompt, budget, primed), priority=priority)
        _boot.note_first_admit()
        now = time.perf_counter()
        self._submitted_at[rid] = now
        self._priority[rid] = priority
        self._usage.begin(rid, int(prompt.size), priority)
        dl = (float(ttft_deadline_ms) if ttft_deadline_ms is not None
              else self._admission.ttft_deadline_ms)
        if dl and dl > 0:
            self._deadline_at[rid] = now + dl / 1e3
        if self._track_progress:
            self._stream[rid] = {"tokens": [], "taken": 0, "done": False}
        if trace is not None and _trace.active():
            self._trace_ids[rid] = trace
            _trace.event("serve/queued", trace=trace, rid=rid,
                         prompt_tokens=int(prompt.size), budget=int(budget),
                         primed=primed is not None, priority=priority,
                         queue_depth=len(self._queue))
        return rid

    def serve_metrics(self, port: int = 0, aggregator=None):
        """Start a /metrics endpoint next to this batcher (exposition.py);
        returns the MetricsServer (read `.port` back when port=0). Pass a
        ClusterAggregator to also accept worker pushes at /push — the
        multi-host serving deployment's one-scrape fleet view."""
        from tfde_tpu.observability.exposition import serve_metrics

        return serve_metrics(port=port, aggregator=aggregator)

    def _publish_stats(self) -> None:
        """Mirror stats() into the metric registry so serving throughput
        rides the /metrics and JSONL exposition paths."""
        reg = metrics.default_registry()
        for k, v in self.stats().items():
            reg.gauge(f"{self._metrics_prefix}/{k}").set(v)
        reg.gauge(f"{self._metrics_prefix}/queue_depth").set(len(self._queue))
        reg.gauge(f"{self._metrics_prefix}/free_rows").set(self.free_rows)
        reg.gauge(f"{self._metrics_prefix}/outstanding_tokens").set(
            self.outstanding_tokens
        )
        reg.gauge(f"{self._metrics_prefix}/queued_tokens").set(
            self.queued_tokens
        )
        reg.gauge(f"{self._metrics_prefix}/drain_rate_tps").set(
            self._admission.drain_rate_tps
        )
        # occupancy + headroom ride every stats publication (including
        # idle steps), so the kv/* gauges track the slab per round
        self.kv_stats()

    # -- hooks --------------------------------------------------------------
    def _validate_submit(self, prompt: np.ndarray,
                         max_new_tokens: int) -> None:
        validate_budget(self._model, int(prompt.size), max_new_tokens)

    def _prefill_wave(self, prompts: np.ndarray, last: np.ndarray,
                      rows: np.ndarray, plens: np.ndarray,
                      n: int) -> np.ndarray:
        """Prefill + scatter one padded admission wave; returns the [R]
        first sampled tokens (host ints). Rows past `n` are ladder
        padding (duplicates of row 0). Subclass-specific: which model(s),
        which caches, which sampling config."""
        raise NotImplementedError

    def _release_row(self, r: int) -> None:
        """Row `r` just left the batch (completion / cancel) — return
        any per-row cache resources. The dense slab has none; the paged
        batcher frees the row's pool blocks and re-points its table at
        the null block."""

    def _admission_cost(self, item) -> int:
        """Pool blocks queue `item` will claim at admission (0 for the
        dense slab, whose per-row cost is the row itself)."""
        return 0

    def _admit_capacity(self, need: int) -> bool:
        """Can the cache grant `need` more blocks right now (free list +
        evictable trie)? The dense slab always can — a free row IS the
        capacity. On False the item goes back to the FRONT of its lane
        and admission stalls until a completion frees blocks."""
        return True

    def _on_capacity_stall(self) -> None:
        """Admission just stalled on cache capacity — a subclass may use
        the pause for bounded maintenance (the paged batcher's
        stall-triggered pool defrag). The dense slab has nothing to
        compact."""

    def _admission_cells(self, kind: str, key, item) -> tuple:
        """(allocated cells, real tokens) one admitted request cost the
        prefill — the ledger's pad-waste unit. Dense: the pad-ladder
        bucket vs the true prompt (suffix for warm groups). The paged
        batcher overrides with block-granular numbers."""
        _rid, prompt, _budget, _pr, _x = item
        if kind == "warm":
            return int(key[1]), int(prompt.size) - int(key[0])
        return int(key), int(prompt.size)

    # -- internals ----------------------------------------------------------
    def _take_token(self, r: int, t: int) -> list:
        """Record a sampled token for row r; frees the row on completion."""
        self._out[r].append(t)
        self._budget[r] -= 1
        self._tok[r] = t
        self._generated += 1
        ent = self._stream.get(self._req[r]) if self._track_progress else None
        if ent is not None:
            ent["tokens"].append(int(t))
        if self._budget[r] <= 0 or (self._eos is not None and t == self._eos):
            if ent is not None:
                ent["done"] = True
            rid = self._req[r]
            n = len(self._out[r])
            t1 = self._first_at.pop(rid, None)
            if t1 is not None and n > 1:
                # decode-side TPOT: first token -> last token, per decode
                # step (the SLO layer's second latency axis)
                tpot_ms = (time.perf_counter() - t1) * 1e3 / (n - 1)
                metrics.default_registry().histogram(
                    "serving/tpot_ms").observe(tpot_ms)
                tid = self._trace_ids.get(rid)
                if tid is not None:
                    _trace.note_exemplar("serving/tpot_ms", tpot_ms, tid)
            tid = self._trace_ids.pop(rid, None)
            if tid is not None:
                _trace.event("serve/done", trace=tid, rid=rid, tokens=n,
                             eos=bool(self._eos is not None
                                      and t == self._eos))
            self._priority.pop(rid, None)
            self._deadline_at.pop(rid, None)
            self._usage.finish(rid, n, outcome="ok")
            done = (rid, np.asarray(self._out[r], np.int32))
            self._release_row(r)
            self._req[r] = None
            self._out[r] = []
            self._committed[r] = 0
            self._tok[r] = self._pad
            return [done]
        return []

    def _plan_wave(self, wave) -> list:
        """Partition one admission wave into prefill groups:
        [(kind, key, items)] where each item is (rid, prompt, budget,
        primed, extra). Base kinds: 'cold' (full prefill) grouped by
        prompt bucket, and 'primed' (K/V in hand — scatter only) also by
        bucket. `ContinuousBatcher` adds 'warm' prefix-cache groups, with
        the matched K/V as `extra`."""
        cold: dict = collections.OrderedDict()
        primed: dict = collections.OrderedDict()
        for rid, prompt, budget, pr in wave:
            bucket = next(b for b in self._buckets if b >= prompt.size)
            dst = primed if pr is not None else cold
            dst.setdefault(bucket, []).append(
                (rid, prompt, budget, pr, None)
            )
        plans = [("cold", b, g) for b, g in cold.items()]
        plans += [("primed", b, g) for b, g in primed.items()]
        return plans

    def _admit_group(self, kind: str, key, group, rows) -> np.ndarray:
        """Run one admission group under the recompile sentinel: every
        prefill wave is a watched jit entry point fingerprinted by
        (batcher, group key, padded wave width), so a mid-serve recompile
        lands in the compile/serve/prefill_<kind>/* counters, the flight
        ring, and — when the wave carries traced requests — the PR-9
        waterfall."""
        rp = _pad_wave(len(group), self._b)
        traces = None
        if self._trace_ids:
            tids = [t for it in group
                    if (t := self._trace_ids.get(it[0])) is not None]
            traces = tids or None
        site = _recompile.site(f"serve/prefill_{kind}")
        with site.watch(self._rc_tag, kind, key, rp, traces=traces):
            return self._run_group(kind, key, group, rows)

    def _run_group(self, kind: str, key, group, rows) -> np.ndarray:
        """Dispatch one admission group to its wave implementation —
        the seam subclasses extend with new admission kinds (the
        sentinel wrapper above stays shared)."""
        if kind == "cold":
            return self._cold_wave(key, group, rows)
        if kind == "primed":
            return self._primed_wave(key, group, rows)
        raise ValueError(f"unknown admission kind {kind!r}")

    def _mem_register(self, name: str, fn, args, donated=None) -> None:
        """Register one serving program with the memory ledger, once per
        (program name, shape signature) per batcher — publishes the
        mem/<name>/* peak/argument/output gauges for every pad-ladder
        bucket the server actually compiles."""
        if name in self._mem_programs:
            return
        self._mem_programs.add(name)
        # the linter rides the same seam: every pad-ladder bucket the
        # server compiles is offered for interrogation (no-op unless
        # armed — tools/lintgate.py / TFDE_HLOLINT)
        _hlolint.offer(name, fn, args=args, donated=donated)
        if _memwatch.enabled():
            _memwatch.register(name, fn, args=args, donated=donated)

    def _cold_wave(self, bucket: int, group, rows) -> np.ndarray:
        n = len(group)
        rp = _pad_wave(n, self._b)
        prompts = np.full((rp, bucket), self._pad, np.int32)
        last = np.zeros(rp, np.int32)
        plens = np.zeros(rp, np.int32)
        rows_pad = np.asarray(rows + [rows[0]] * (rp - n), np.int32)
        for i in range(rp):
            # wave padding repeats row 0's request verbatim: the
            # duplicate prefill K/V is bit-identical (prefill is
            # row-independent and deterministic), so the duplicate
            # cache-scatter writes never race on ordering
            _rid, prompt, _budget, _pr, _x = group[i if i < n else 0]
            prompts[i, :prompt.size] = prompt
            last[i] = prompt.size - 1
            plens[i] = prompt.size
        return self._prefill_wave(prompts, last, rows_pad, plens, n)

    def _primed_wave(self, bucket: int, group, rows) -> np.ndarray:
        raise NotImplementedError(
            "primed admission requires a subclass with _accepts_primed"
        )

    def _admit(self) -> list:
        """Fill free rows from the queue, a GROUP WAVE at a time: every
        freed row whose next request shares an admission group (cold
        prompt bucket / warm prefix length / primed bucket) prefills in
        one call and lands with one multi-row scatter. The prefill
        samples each row's first token in-program (generate's prefill
        contract), so every active row uniformly holds one pending token
        afterwards. A request finishing on its first token (budget 1 /
        instant EOS) frees its row for the next queued request within
        the same call."""
        finished = []
        reg = metrics.default_registry()
        stalled = False
        while self._queue and self.free_rows and not stalled:
            free = [r for r in range(self._b) if self._req[r] is None]
            wave = []
            reserved = 0
            while self._queue and len(wave) < len(free):
                item = self._queue.popleft()
                # deadline shed happens HERE, at dequeue: a request whose
                # queue wait alone already blew its TTFT budget is dead
                # on arrival to the client — prefilling it would spend a
                # wave on tokens nobody is waiting for
                if self._maybe_shed(item):
                    continue
                # block-capacity gate (paged only): a request whose
                # lifetime blocks don't fit the pool right now goes BACK
                # to the front of its lane — admission stalls (head-of-
                # line, deliberately: skipping ahead would starve big
                # requests forever) until completions free blocks
                need = self._admission_cost(item)
                if need and not self._admit_capacity(reserved + need):
                    self._requeue_front(item)
                    reg.counter("serving/admit_capacity_stall").incr()
                    self._on_capacity_stall()
                    stalled = True
                    break
                reserved += need
                wave.append(item)
            taken = 0
            for kind, key, group in self._plan_wave(wave):
                n = len(group)
                rows = free[taken:taken + n]
                taken += n
                t_wave = time.perf_counter()
                wall_wave = time.time()
                with span("serving/prefill"):
                    toks = self._admit_group(kind, key, group, rows)
                # admission waves in the flight ring: one event per wave
                # (not per request), enough to reconstruct the admit/queue
                # rhythm in a serving post-mortem
                from tfde_tpu.observability import flightrec

                flightrec.record(
                    "admit", rows=n, group=kind,
                    key=list(key) if isinstance(key, tuple) else int(key),
                    queue_depth=len(self._queue),
                )
                now = time.perf_counter()
                if self._trace_ids:
                    tids = [self._trace_ids.get(it[0]) for it in group]
                    if any(tids):
                        # one wave slice tagged with EVERY member trace:
                        # the waterfall shows who shared the prefill
                        _trace.event(
                            f"serve/prefill_{kind}", traces=tids,
                            ts=wall_wave, dur=now - t_wave, rows=n,
                            key=list(key) if isinstance(key, tuple)
                            else int(key),
                        )
                # pad-ladder accounting: the prefill program computed/
                # wrote `alloc` cells per row (the group's bucket; for
                # warm groups only the SUFFIX bucket — the prefix K/V
                # landed unpadded; for paged groups the FRESH BLOCKS
                # granted, so the histogram reads intra-block slack), of
                # which each request's true token count is real — the
                # rest is the waste the ledger sizes paged-KV's win by
                for i, (rid, prompt, budget, _pr, _x) in enumerate(group):
                    r = rows[i]
                    self._req[r] = rid
                    self._out[r] = []
                    self._budget[r] = budget
                    self._committed[r] = prompt.size
                    if self._ledger is not None:
                        alloc, used = self._admission_cells(
                            kind, key, group[i])
                        self._ledger.note_admission(kind, alloc, int(used))
                    self._usage.admitted(rid)
                    t0 = self._submitted_at.pop(rid, None)
                    self._first_at[rid] = now
                    # cold-start edge: the boot ledger's first served
                    # token (idempotent after the first request)
                    _boot.note_first_token()
                    if t0 is not None:
                        # the TTFT decomposition the bench reports:
                        # queue_wait (submit -> wave start) + prefill
                        # (the serving/prefill span) = first token
                        queue_ms = (t_wave - t0) * 1e3
                        ttft_ms = (now - t0) * 1e3
                        reg.histogram("serving/queue_wait_ms").observe(
                            queue_ms
                        )
                        reg.histogram("serving/ttft_ms").observe(ttft_ms)
                        tid = self._trace_ids.get(rid)
                        if tid is not None:
                            _trace.event(
                                "serve/first_token", trace=tid, rid=rid,
                                kind=kind, ttft_ms=round(ttft_ms, 3),
                                queue_wait_ms=round(queue_ms, 3),
                            )
                            _trace.note_exemplar("serving/ttft_ms",
                                                 ttft_ms, tid)
                    finished.extend(self._take_token(r, int(toks[i])))
            self._mark_dirty()
        return finished

    def _requeue_front(self, item) -> None:
        """Put a dequeued-but-not-admittable item back at the head of
        its priority lane (capacity stall — nothing overtakes it)."""
        self._queue.appendleft(
            item,
            priority=self._priority.get(item[0],
                                        _admission.DEFAULT_PRIORITY),
        )

    def _maybe_shed(self, item) -> bool:
        """Deadline/TTL shedding: True when `item`'s queue wait already
        exceeds its TTFT deadline — the request is dropped (no prefill),
        its stream entry flips to done+shed, and `was_shed` answers once
        so the HTTP layer can report it explicitly."""
        rid, _prompt, budget, _pr = item
        dl = self._deadline_at.get(rid)
        if dl is None or time.perf_counter() <= dl:
            return False
        pr = self._priority.pop(rid, _admission.DEFAULT_PRIORITY)
        self._deadline_at.pop(rid, None)
        t0 = self._submitted_at.pop(rid, None)
        self._first_at.pop(rid, None)
        waited_ms = ((time.perf_counter() - t0) * 1e3
                     if t0 is not None else None)
        self._shed.add(rid)
        ent = self._stream.get(rid)
        if ent is not None:
            ent["done"] = True
            ent["shed"] = True
        reg = metrics.default_registry()
        reg.counter("serving/shed_expired").incr()
        reg.counter(f"serving/shed_{pr}").incr()
        reg.counter("serving/shed_tokens").incr(int(budget))
        self._usage.finish(rid, 0, outcome="shed")
        tid = self._trace_ids.pop(rid, None)
        if tid is not None:
            _trace.event("serve/shed", trace=tid, rid=rid, priority=pr,
                         waited_ms=round(waited_ms, 3)
                         if waited_ms is not None else None)
        from tfde_tpu.observability import flightrec

        flightrec.record("shed", rid=rid, priority=pr,
                         waited_ms=waited_ms, budget=int(budget))
        return True

    def _mark_dirty(self) -> None:
        """Admission invalidated the device-resident loop state (if the
        subclass keeps any)."""


class ContinuousBatcher(_BatcherBase):
    """Fixed-batch continuous serving loop over a causal LM.

    model/params: a decode-capable model (GPT family) and its params.
    batch_size: resident decode rows. max_len: per-row cache budget
    (prompt + generated must fit). scan_depth: ceiling K on fused decode
    ticks per host round-trip (see the module docstring; 1 restores the
    one-tick-per-step behavior). The sampling config is fixed per
    batcher, as for `generate`.

    prefix_cache: a `prefix_cache.PrefixCache`, True/int (default
    budget / byte budget), or None to defer to ``TFDE_PREFIX_CACHE`` —
    admissions whose prompt prefix is cached skip straight to suffix
    prefill (`_warm_wave`), bit-identical under greedy decoding.
    role: 'both' (default), 'prefill' (serve `prime()` only — the
    hand-off producer of the prefill/decode split), or 'decode'
    (refuses `prime()`; accepts `submit_primed()` hand-offs alongside
    plain submits). inference/router.py wires these across processes.

    Usage::

        srv = ContinuousBatcher(model, params, batch_size=4, max_len=256)
        rid = srv.submit(prompt_1d, max_new_tokens=64)
        while not srv.idle:
            for req_id, tokens in srv.step():
                ...   # finished requests, completion order

    `step()` admits queued requests into free rows (bucketed wave
    prefill) and runs ONE fused decode scan of up to `scan_depth` ticks;
    it returns the requests finishing on that call. `run()` drains
    everything.
    """

    _metrics_prefix = "serving/batcher"

    def __init__(
        self,
        model,
        params,
        batch_size: int,
        max_len: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: float = 1.0,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        rng: Optional[jax.Array] = None,
        prompt_buckets: Optional[tuple] = None,
        scan_depth: int = 4,
        prefix_cache=None,
        role: str = "both",
        admission_ctl=None,
        paged: Optional[bool] = None,
        pool_blocks: Optional[int] = None,
        kv_quant: Optional[str] = None,
    ):
        if repetition_penalty <= 0.0:
            raise ValueError(
                f"repetition_penalty must be > 0 (1.0 = off), got "
                f"{repetition_penalty}"
            )
        if scan_depth < 1:
            raise ValueError(f"scan_depth must be >= 1, got {scan_depth}")
        super().__init__(model, params, batch_size, max_len, eos_id,
                         pad_id, rng, prompt_buckets, role=role,
                         admission_ctl=admission_ctl)
        # quantized KV cache (TFDE_KV_QUANT, ops/quant.kv_quantize): int8
        # payload + fp32 scale sidecars in every cache layout this batcher
        # builds — the batch slab/pool, the row templates, the prefix trie
        # slices and the primed hand-off all inherit the leaf set from
        # init_cache, so ONE resolution here covers them all. 'fp' (the
        # default) keeps every tree and program byte-identical to before.
        kvq = (knobs.env_choice("TFDE_KV_QUANT") if kv_quant is None
               else str(kv_quant))
        self._kv_quant = None if kvq == "fp" else kvq
        self._decode_model = _decode_clone(model, kv_quant=self._kv_quant)
        self._sampling = dict(
            temperature=float(temperature),
            top_k=top_k, top_p=top_p, min_p=min_p,
            repetition_penalty=float(repetition_penalty),
        )
        self._scan_depth = int(scan_depth)
        # presence mask for the repetition penalty (per row, prompt ids
        # included — the generate() convention); lives ON DEVICE and is
        # threaded through the fused scan, so steady-state ticks ship no
        # [B, vocab] host copies and no host-driven scatters
        self._seen = (
            jnp.zeros((batch_size, model.vocab_size), bool)
            if repetition_penalty != 1.0 else None
        )
        self._vocab = model.vocab_size

        # paged KV (TFDE_PAGED_KV, inference/paged.py): swap the dense
        # per-row slab for the shared block pool + per-row block tables.
        # `paged=None` defers to the knob; the dense path below stays
        # byte-identical when off. `self._decode_model` remains the
        # DENSE clone either way — prime() and the row templates speak
        # the dense layout (the primed hand-off is layout-agnostic);
        # only the resident batch cache and its programs go paged.
        self._paged = (knobs.env_flag("TFDE_PAGED_KV") if paged is None
                       else bool(paged))
        if self._paged:
            block = DEFAULT_BLOCK
            self._kv_block = int(block)
            # +1 cell: the decode scan writes one-past-committed for
            # frozen rows, so a full row still has a mapped (or null)
            # slot to take the junk write
            self._nmax = -(-(self._max_len + 1) // block)
            self._chunk = min(
                max(1, knobs.env_int("TFDE_PAGED_PREFILL_CHUNK")),
                self._max_len,
            )
            # default pool: every row can hold a full table, plus the
            # null block — capacity-neutral vs the dense slab; size it
            # DOWN (the bench's A/B) to serve more rows than the dense
            # slab could under the same byte envelope
            nblocks = (int(pool_blocks) if pool_blocks is not None
                       else batch_size * self._nmax + 1)
            if nblocks < self._nmax + 1:
                raise ValueError(
                    f"pool_blocks={nblocks} cannot hold even one "
                    f"max-length row ({self._nmax} blocks + null)"
                )
            self._paged_model = _decode_clone(
                model, paged_blocks=nblocks, kv_block=block,
                kv_quant=self._kv_quant)
            raw = init_cache(model, batch_size, self._max_len,
                             paged_blocks=nblocks, kv_block=block,
                             kv_quant=self._kv_quant)
            self._pool = _paged.BlockPool(nblocks, block)
            self._tables = np.zeros((batch_size, self._nmax), np.int32)
            self._row_blocks: list = [[] for _ in range(batch_size)]
            self._shared_cells = np.zeros(batch_size, np.int64)
            self._tables_dirty = False
            # dense batch shapes (abstract — never materialized) still
            # seed the row templates below: prime() prefills on the
            # dense row layout
            raw_shapes = jax.eval_shape(functools.partial(
                init_cache, model, batch_size, self._max_len,
                kv_quant=self._kv_quant))
        else:
            self._paged_model = None
            self._pool = None
            raw = init_cache(model, batch_size, self._max_len,
                             kv_quant=self._kv_quant)
            raw_shapes = raw
        # the decode scan's model: paged clone when on, dense otherwise
        self._scan_model = self._paged_model or self._decode_model
        # index leaves become [B] vectors ONCE, so the scan carry shape is
        # stable from the first tick (the per-row decode-attention branch)
        self._cache = _set_index_counters(
            raw, np.zeros(batch_size, np.int32)
        )
        # row-cache SHAPES for every admission-wave width on the pad
        # ladder, derived AT CONSTRUCTION: init_cache is a full flax
        # eval_shape trace (~50ms) — paid lazily it lands in the first
        # wave's TTFT. One extra rp=1 trace identifies the batch-carrying
        # leaves (their shapes differ from the batch cache's); the other
        # widths are pure shape substitution. _prefill_rows /
        # _prefill_suffix DONATE their cache argument (no device-side K/V
        # copy per wave), so each wave materializes fresh zeros into the
        # donated slot instead of reusing a live template.
        self._row_shapes: dict = {}
        one = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            init_cache(model, 1, self._max_len, kv_quant=self._kv_quant),
        )
        rp = 1
        while True:
            self._row_shapes[rp] = jax.tree.map(
                lambda s1, ab, _rp=rp: s1 if s1.shape == ab.shape
                else jax.ShapeDtypeStruct(
                    (_rp,) + s1.shape[1:], s1.dtype
                ),
                one, raw_shapes,
            )
            if rp >= batch_size:
                break
            rp = min(rp * 2, batch_size)
        # prefix-KV cache: None = every admission cold. Paged mode
        # builds the trie over the POOL (block ids, zero-copy sharing)
        # and registers it as the pool's eviction valve — allocation
        # pressure drains cached prefixes LRU-first
        if self._paged:
            block_bytes = _paged.pool_bytes(self._cache) / float(nblocks)
            self._prefix = _paged.resolve_paged(
                prefix_cache, self._pool, block_bytes)
            if self._prefix is not None:
                self._pool.set_evictor(self._prefix.evict)
        else:
            self._prefix = _resolve_prefix(prefix_cache)
        # device-resident loop state (tok/idx/budget/done); rebuilt from
        # host bookkeeping whenever admission desyncs it
        self._dev = None
        self._init_capacity(self._cache)

    # -- public -------------------------------------------------------------
    def stats(self) -> dict:
        """Serving throughput and host-overhead accounting: decode ticks
        run, tokens delivered, tokens/round (mean occupied rows per
        tick), and the per-token host cost — jitted dispatches and
        blocking syncs per generated token (the O(1/K) bound the fused
        scan exists for; tests/test_server.py guards it)."""
        g = max(self._generated, 1)
        return {
            "rounds": self._rounds,
            "generated": self._generated,
            "tokens_per_round": self._generated / max(self._rounds, 1),
            "dispatches": self._dispatches,
            "syncs": self._syncs,
            "dispatches_per_token": self._dispatches / g,
            "syncs_per_token": self._syncs / g,
        }

    def step(self) -> list:
        """Admit into free rows, run one fused decode scan (up to
        `scan_depth` ticks); returns [(request_id, tokens 1-D np.int32),
        ...] that finished now."""
        with span("serving/admit"):
            finished = self._admit()
        active = [r for r in range(self._b) if self._req[r] is not None]
        if not active:
            self._publish_stats()
            return finished

        depth = self._pick_depth(active)
        traced = (
            [self._trace_ids[rid] for r in active
             if (rid := self._req[r]) in self._trace_ids]
            if self._trace_ids else []
        )
        t0 = time.perf_counter()
        with span("serving/decode"):
            if self._paged and self._tables_dirty:
                # a released row's DEVICE table still points at its old
                # blocks, and the frozen row keeps writing pad K/V at
                # its stale position every tick — re-point it at the
                # null block BEFORE any compiled program runs, or a
                # reallocated block would take those writes
                self._cache = _paged.set_block_tables(
                    self._cache, self._tables)
                self._tables_dirty = False
                self._dispatches += 1
            if self._dev is None:
                self._upload_state()
            tok, idx, budget, done = self._dev
            rng = self._rng if self._sampling["temperature"] != 0.0 else None
            self._mem_register(
                f"serve/decode/k{depth}",
                functools.partial(
                    _decode_scan, self._scan_model, depth=depth,
                    eos_id=self._eos, pad_id=self._pad, **self._sampling,
                ),
                (self._cache, self._params, tok, idx, budget, done,
                 self._seen, rng),
                donated=(self._cache, tok, idx, budget, done, self._seen),
            )
            # steady-state decode is the shape-stable site: the depth
            # ladder gives O(log scan_depth) expected signatures, and any
            # repeat-fingerprint miss is an unexpected recompile (the
            # per-token-recompile pathology memgate pins)
            rc = _recompile.site("serve/decode", stable=True)
            with rc.watch(self._rc_tag, depth, traces=traced or None):
                out = _decode_scan(
                    self._scan_model, self._cache, self._params, tok, idx,
                    budget, done, self._seen, rng, depth=depth,
                    eos_id=self._eos, pad_id=self._pad, **self._sampling,
                )
            self._dispatches += 1
            (self._cache, tok, idx, budget, done, self._seen, rng,
             toks, emitted) = out
            self._dev = (tok, idx, budget, done)
            if rng is not None:
                self._rng = rng
            toks_np, emitted_np = _fetch((toks, emitted))
            self._syncs += 1
        self._rounds += depth
        self._profiler_round(traced)
        n_emitted = 0
        for r in active:
            row = toks_np[r][emitted_np[r]]
            if row.size == 0:
                continue
            n_emitted += int(row.size)
            # feeding each pending token committed it; the row's last
            # sample stays pending
            self._committed[r] += int(row.size)
            for t in row:
                finished.extend(self._take_token(r, int(t)))
        dt = time.perf_counter() - t0
        if traced:
            _trace.event("serve/decode_round", traces=traced, dur=dt,
                         depth=depth, rows=len(active), emitted=n_emitted)
        if n_emitted:
            metrics.default_registry().histogram(
                "serving/ms_per_token"
            ).observe(dt * 1e3 / n_emitted)
            self._admission.note_drain(n_emitted, dt)
        self._publish_stats()
        return finished

    # -- internals ----------------------------------------------------------
    def _validate_submit(self, prompt, max_new_tokens) -> None:
        if self._seen is not None and (
                prompt.min() < 0 or prompt.max() >= self._vocab):
            # queue-time, not admission-time (the _normalize_buckets rule):
            # jnp .at scatters DROP out-of-bounds updates silently, so an
            # over-vocab id would simply go un-penalized and a negative id
            # would mark the wrong entry via wraparound — no crash, just
            # quietly wrong sampling; refuse here instead
            raise ValueError(
                f"prompt ids must lie in [0, {self._vocab}) when "
                f"repetition_penalty is on; got "
                f"[{int(prompt.min())}, {int(prompt.max())}]"
            )
        if self._paged:
            need = _paged.blocks_for(
                int(prompt.size) + int(max_new_tokens) + 1,
                self._kv_block)
            if need > self._pool.num_blocks - 1:
                # queue-time, like the vocab check: the capacity gate
                # would requeue this request at the lane head forever
                raise ValueError(
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self._pool.num_blocks - 1}; raise pool_blocks or "
                    f"shrink the request"
                )
        super()._validate_submit(prompt, max_new_tokens)

    def _pick_depth(self, active) -> int:
        """K for this scan. Queue waiting: bound by the SOONEST possible
        row completion so admission latency never exceeds one short scan.
        Queue empty: bound by the LONGEST remaining budget so the
        draining tail runs no dead ticks. (EOS completions are not
        host-predictable; a mid-scan EOS freezes the row on device and
        wastes at most K-1 of its ticks.)"""
        if self._scan_depth == 1:
            return 1
        remaining = [int(self._budget[r]) for r in active]
        bound = min(remaining) if self._queue else max(remaining)
        return _ladder_depth(self._scan_depth, bound)

    def _mark_dirty(self) -> None:
        self._dev = None

    def _upload_state(self) -> None:
        """Rebuild the device loop state from host bookkeeping (after
        admission; steady state reuses the scan's own carry outputs)."""
        self._dev = (
            jnp.asarray(self._tok, jnp.int32),
            jnp.asarray(self._committed, jnp.int32),
            jnp.asarray(self._budget, jnp.int32),
            jnp.asarray(np.asarray([r is None for r in self._req])),
        )
        self._dispatches += 1  # the four small host->device transfers

    def _row_template(self, rp: int):
        """FRESH zero row cache for a donated prefill call, materialized
        from shapes cached per wave size (the donation consumed the last
        one — reusing it would hand jit a deleted buffer)."""
        if rp not in self._row_shapes:
            self._row_shapes[rp] = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                init_cache(self._model, rp, self._max_len,
                           kv_quant=self._kv_quant),
            )
        self._dispatches += 1
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            self._row_shapes[rp])

    def _prefill_wave(self, prompts, last, rows, plens, n) -> np.ndarray:
        rp, bucket = prompts.shape
        valid = None
        if self._seen is not None:
            valid = jnp.asarray(
                np.arange(bucket)[None, :] < plens[:, None]
            )
        rng = None
        if self._sampling["temperature"] != 0.0:
            self._rng, rng = jax.random.split(self._rng)
        tmpl = self._row_template(rp)
        prompts_dev = jnp.asarray(prompts)
        last_dev = jnp.asarray(last)
        self._mem_register(
            f"serve/prefill/b{bucket}r{rp}",
            functools.partial(_prefill_rows, self._decode_model,
                              **self._sampling),
            (tmpl, self._params, prompts_dev, last_dev, valid, rng),
            donated=tmpl,
        )
        row_cache, tok, row_seen = _prefill_rows(
            self._decode_model, tmpl, self._params,
            prompts_dev, last_dev, valid, rng,
            **self._sampling,
        )
        self._dispatches += 1
        if self._prefix is not None:
            # cold admissions SEED the prefix cache: store each real
            # row's complete prompt blocks before the scatter consumes
            # our interest in row_cache (slices are fresh buffers, so
            # the donated-output aliasing never bites)
            for i in range(n):
                self._prefix.insert(prompts[i, :plens[i]], row_cache, i)
        rows_dev = jnp.asarray(rows)
        self._cache = _scatter_rows(self._cache, row_cache, rows_dev)
        self._dispatches += 1
        if row_seen is not None:
            if rp > n:
                # a ladder-padding row's K/V duplicates row 0 bit-exactly,
                # but its sampled-first-token seen bit can differ under
                # temperature>0 (independent categorical draw per row) —
                # gather duplicates back to row 0's seen so the duplicate
                # scatter indices below write identical values
                sel = np.arange(rp)
                sel[n:] = 0
                row_seen = row_seen[jnp.asarray(sel)]
            self._seen = self._seen.at[rows_dev].set(row_seen)
            self._dispatches += 1
        tok_np = _fetch(tok)
        self._syncs += 1
        return tok_np

    # -- prefix cache (warm admission) ---------------------------------------
    _accepts_primed = True

    @property
    def prefix_cache(self):
        return self._prefix

    def _plan_wave(self, wave) -> list:
        if self._paged:
            return self._plan_paged_wave(wave)
        if self._prefix is None:
            return super()._plan_wave(wave)
        cold: dict = collections.OrderedDict()
        warm: dict = collections.OrderedDict()
        primed: dict = collections.OrderedDict()
        for rid, prompt, budget, pr in wave:
            bucket = next(b for b in self._buckets if b >= prompt.size)
            if pr is not None:
                primed.setdefault(bucket, []).append(
                    (rid, prompt, budget, pr, None)
                )
                continue
            pre_len, kv = self._prefix.lookup(
                prompt, trace=self._trace_ids.get(rid)
            )
            # the suffix feeds at cache position pre_len, so its bucket
            # must ALSO fit the row: pre_len + sbucket <= max_len, or the
            # transformer's clamped dynamic_update_slice would silently
            # overwrite the scattered prefix K/V. Shorten the used prefix
            # (whole blocks) until a bucket fits; pre_len 0 is a cold
            # admission, whose full-prompt bucket always fits.
            matched, sbucket = pre_len, None
            while pre_len:
                suffix = prompt.size - pre_len
                sbucket = next(
                    (b for b in self._buckets
                     if b >= suffix and pre_len + b <= self._max_len),
                    None,
                )
                if sbucket is not None:
                    break
                pre_len -= self._prefix.block
            if pre_len:
                if pre_len < matched:
                    kv = {name: a[:pre_len] for name, a in kv.items()}
                # the full-prompt bucket only shapes the program when the
                # repetition penalty needs the whole prompt's presence
                # mask; keying on it otherwise would split waves for no
                # compile reason
                fbucket = bucket if self._seen is not None else 0
                warm.setdefault((pre_len, sbucket, fbucket), []).append(
                    (rid, prompt, budget, None, kv)
                )
            else:
                cold.setdefault(bucket, []).append(
                    (rid, prompt, budget, None, None)
                )
        plans = [("cold", b, g) for b, g in cold.items()]
        plans += [("warm", k, g) for k, g in warm.items()]
        plans += [("primed", b, g) for b, g in primed.items()]
        return plans

    def _run_group(self, kind: str, key, group, rows) -> np.ndarray:
        if kind == "warm":
            return self._warm_wave(key, group, rows)
        if kind == "paged":
            return self._paged_wave(key, group, rows)
        return super()._run_group(kind, key, group, rows)

    # -- paged KV (TFDE_PAGED_KV) --------------------------------------------
    @property
    def paged(self) -> bool:
        return self._paged

    @property
    def block_pool(self):
        """The shared BlockPool (None when dense) — bench/tests read
        its stats; nothing else should allocate from it."""
        return self._pool

    def _init_capacity(self, cache, cells_per_row=None) -> None:
        if not self._paged:
            return super()._init_capacity(cache, cells_per_row)
        cells = int(cells_per_row if cells_per_row is not None
                    else self._max_len)
        self._ledger = _capacity.PagedCapacityLedger(
            self._b, cells, _paged.pool_bytes(cache),
            self._pool.num_blocks, self._kv_block, self._paged_snapshot,
            census=_capacity.kv_dtype_census(cache),
        )
        self._cap_model = _capacity.PagedCapacityModel(self._ledger)

    def _paged_snapshot(self) -> dict:
        """The paged ledger's duck-typed pool view (observability never
        imports inference): pool stats + the trie/sharing split."""
        snap = self._pool.stats()
        snap["trie_blocks"] = (self._prefix.segments
                               if self._prefix is not None else 0)
        snap["shared_cells"] = int(self._shared_cells.sum())
        return snap

    def _release_row(self, r: int) -> None:
        if not self._paged:
            return
        if self._row_blocks[r]:
            self._pool.free(self._row_blocks[r])
            self._row_blocks[r] = []
        self._tables[r, :] = 0
        self._shared_cells[r] = 0
        # the device copy of this table still points at the freed
        # blocks; step()/the next wave re-uploads before any program
        # runs (the freed-row junk-write hazard)
        self._tables_dirty = True

    def _admission_cost(self, item) -> int:
        if not self._paged:
            return 0
        _rid, prompt, budget, _pr = item
        # full lifetime, sharing ignored: a warm match only lowers the
        # real claim, so the gate errs toward stalling one wave early,
        # never toward PoolExhausted mid-wave
        return _paged.blocks_for(int(prompt.size) + int(budget) + 1,
                                 self._kv_block)

    def _admit_capacity(self, need: int) -> bool:
        if not self._paged:
            return True
        evictable = (self._prefix.evictable_blocks()
                     if self._prefix is not None else 0)
        return self._pool.available(evictable) >= need

    def _on_capacity_stall(self) -> None:
        """Admission stalled on the pool: spend the pause compacting.

        Fixed-size blocks can never fragment *allocatability* (any free
        block serves any request), so this is purely a locality pass —
        it squeezes live ids toward the bottom of the pool so gathers
        walk a dense span.  Safe exactly here because the stall breaks
        out of wave COLLECTION, before _plan_paged_wave claims warm
        blocks: the only id holders are _row_blocks, the trie nodes and
        the host tables, and all three are rewritten below.  The device
        block_table copies go stale, so _tables_dirty forces a
        re-upload before any program runs."""
        if not self._paged:
            return
        thr = knobs.env_float("TFDE_KV_DEFRAG_THRESHOLD")
        if not thr or thr <= 0:
            return
        frag = self._pool.fragmentation()
        if frag < thr:
            return
        plan = self._pool.defrag()
        if not plan:
            return
        self._cache, self._tables = _paged.apply_defrag(
            self._cache, self._tables, plan)
        self._row_blocks = [[plan.get(int(b), int(b)) for b in row]
                            for row in self._row_blocks]
        if self._prefix is not None:
            self._prefix.remap(plan)
        self._tables_dirty = True
        metrics.default_registry().counter("kv/pool_defrags").incr()
        from tfde_tpu.observability import flightrec
        flightrec.record("kv_defrag", moved=len(plan),
                         frag=round(float(frag), 3),
                         free=self._pool.free_blocks)

    def _admission_cells(self, kind: str, key, item) -> tuple:
        if not self._paged:
            return super()._admission_cells(kind, key, item)
        _rid, prompt, _budget, _pr, extra = item
        pre = int(extra[0]) if (kind == "paged" and extra is not None) else 0
        block = self._kv_block
        alloc = (_paged.blocks_for(int(prompt.size), block)
                 - pre // block) * block
        return alloc, int(prompt.size) - pre

    def _plan_paged_wave(self, wave) -> list:
        """Paged admission planning: cold and warm collapse into ONE
        'paged' group — the chunk program is shape-blind to prompt
        length and wave membership, so there is nothing to group BY
        except the chunk width (its only static). Primed hand-offs keep
        their per-bucket grouping (the shipped K/V stack is shaped by
        the bucket). Warm lookups CLAIM their matched blocks here at
        plan time (incref), so nothing between plan and wave — another
        item's allocation draining the trie included — can invalidate
        the ids; the claim is the row's own reference, released with
        the rest of its blocks. A same-wave duplicate prompt still
        misses (its twin's blocks enter the trie only after the wave) —
        the dense intra-wave semantics."""
        items: list = []
        primed: dict = collections.OrderedDict()
        for rid, prompt, budget, pr in wave:
            if pr is not None:
                bucket = next(b for b in self._buckets
                              if b >= prompt.size)
                primed.setdefault(bucket, []).append(
                    (rid, prompt, budget, pr, None)
                )
                continue
            pre_len, ids = 0, None
            if self._prefix is not None:
                pre_len, ids = self._prefix.lookup(
                    prompt, trace=self._trace_ids.get(rid), claim=True)
            items.append((rid, prompt, budget, None, (pre_len, ids)))
        plans = [("paged", self._chunk, items)] if items else []
        plans += [("primed", b, g) for b, g in primed.items()]
        return plans

    def _paged_wave(self, chunk: int, group, rows) -> np.ndarray:
        """Admit a paged wave: point each row's table at its claimed
        trie blocks plus freshly-allocated lifetime blocks, then feed
        every suffix through the ONE full-batch chunk program — warm
        admission's prefix cost is the incref, not a scatter.

        Non-wave rows ride along as pad feeds at their committed index
        (their junk lands in their own uncommitted cells or the null
        block); exhausted wave rows pad at their prompt end. After the
        last chunk each admitting row's true last-position logits sit
        in the [B, V] carry; one small ladder-width program samples the
        first tokens. Cold rows then seed the trie by ADOPTING their
        own complete prompt blocks (incref — zero copy)."""
        n = len(group)
        block = self._kv_block
        starts = np.zeros(n, np.int64)
        plens = np.zeros(n, np.int64)
        for i, (rid, prompt, budget, _pr, extra) in enumerate(group):
            r = rows[i]
            pre_len, ids = extra if extra is not None else (0, None)
            shared = [int(b) for b in ids] if ids else []
            nblk = _paged.blocks_for(prompt.size + budget + 1, block)
            fresh = self._pool.alloc(nblk - len(shared))
            held = shared + fresh
            self._row_blocks[r] = held
            self._tables[r, :len(held)] = held
            self._tables[r, len(held):] = 0
            self._shared_cells[r] = pre_len
            starts[i] = pre_len
            plens[i] = prompt.size
        self._cache = _paged.set_block_tables(self._cache, self._tables)
        self._tables_dirty = False
        self._dispatches += 1
        nchunks = -(-int((plens - starts).max()) // chunk)
        prev = jnp.zeros((self._b, self._vocab), jnp.float32)
        for j in range(nchunks):
            tokens = np.full((self._b, chunk), self._pad, np.int32)
            idx = np.asarray(self._committed, np.int32)
            take = np.zeros(self._b, bool)
            last_in = np.zeros(self._b, np.int32)
            for i in range(n):
                r = rows[i]
                prompt = group[i][1]
                s = int(starts[i]) + j * chunk
                e = min(int(plens[i]), s + chunk)
                if s < plens[i]:
                    tokens[r, :e - s] = prompt[s:e]
                    idx[r] = s
                    if e == plens[i]:
                        take[r] = True
                        last_in[r] = e - 1 - s
                else:
                    idx[r] = plens[i]  # exhausted: pads beyond own prompt
            args = (self._cache, self._params, jnp.asarray(tokens),
                    jnp.asarray(idx), jnp.asarray(take),
                    jnp.asarray(last_in), prev)
            self._mem_register(
                f"serve/prefill_paged/c{chunk}",
                functools.partial(_paged_prefill_chunk, self._paged_model),
                args, donated=self._cache,
            )
            self._cache, prev = _paged_prefill_chunk(
                self._paged_model, *args)
            self._dispatches += 1
        rp = _pad_wave(n, self._b)
        pick = np.asarray([rows[i if i < n else 0] for i in range(rp)],
                          np.int32)
        wave_logits = prev[jnp.asarray(pick)]
        self._dispatches += 1
        seen_dev = None
        if self._seen is not None:
            seen_rows = np.zeros((rp, self._vocab), bool)
            for i in range(rp):
                seen_rows[i, group[i if i < n else 0][1]] = True
            seen_dev = jnp.asarray(seen_rows)
        rng = None
        if self._sampling["temperature"] != 0.0:
            self._rng, rng = jax.random.split(self._rng)
        tok, seen_out = _sample_first(wave_logits, rng, seen_dev,
                                      **self._sampling)
        self._dispatches += 1
        if seen_out is not None:
            rows_pad = np.asarray(
                list(rows) + [rows[0]] * (rp - n), np.int32)
            if rp > n:
                # the dense dup-row rule: duplicate scatter targets must
                # carry identical values (padding rows drew their own
                # first token under temperature > 0)
                sel = np.arange(rp)
                sel[n:] = 0
                seen_out = seen_out[jnp.asarray(sel)]
            self._seen = self._seen.at[jnp.asarray(rows_pad)].set(seen_out)
            self._dispatches += 1
        tok_np = _fetch(tok)
        self._syncs += 1
        if self._prefix is not None:
            for i in range(n):
                _rid, prompt, _budget, _pr, extra = group[i]
                if extra is not None and extra[0]:
                    continue  # warm rows don't re-insert (dense parity)
                nb = prompt.size // block
                if nb:
                    self._prefix.insert(
                        prompt, self._row_blocks[rows[i]][:nb])
        return tok_np

    def _primed_paged_wave(self, bucket: int, group, rows) -> np.ndarray:
        """Primed hand-off under paging: allocate each row's lifetime
        blocks, re-chunk the shipped host K/V (dense leaf names,
        layout-agnostic [P, ...] segments) to block granularity, and
        land it with ONE donated pool scatter — still zero model flops
        on the decode replica. Compiled per (bucket, wave width) like
        the dense primed path: the K/V stack is shipped data; there is
        no program to collapse."""
        n = len(group)
        block = self._kv_block
        rp = _pad_wave(n, self._b)
        nb_bucket = _paged.blocks_for(bucket, block)
        blk = np.zeros((rp, nb_bucket), np.int32)
        toks = np.zeros(rp, np.int64)
        seen_rows = (
            np.zeros((rp, self._vocab), bool)
            if self._seen is not None else None
        )
        sample = group[0][3].kv
        stacked = {
            _paged.pool_leaf_name(name): np.zeros(
                (rp, nb_bucket, block) + arr.shape[1:], arr.dtype)
            for name, arr in sample.items()
        }
        for i in range(rp):
            _rid, prompt, budget, pr, _x = group[i if i < n else 0]
            if i < n:
                r = rows[i]
                nblk = _paged.blocks_for(prompt.size + budget + 1, block)
                fresh = self._pool.alloc(nblk)
                self._row_blocks[r] = fresh
                self._tables[r, :nblk] = fresh
                self._tables[r, nblk:] = 0
                self._shared_cells[r] = 0
                nbp = _paged.blocks_for(prompt.size, block)
                blk[i, :nbp] = fresh[:nbp]
                for name, arr in pr.kv.items():
                    dst = stacked[_paged.pool_leaf_name(name)]
                    flat = dst[i].reshape(
                        (nb_bucket * block,) + arr.shape[1:])
                    flat[:arr.shape[0]] = arr
            # padding rows (i >= n) keep null targets AND zero payload:
            # every duplicate write to block 0 lands the same zeros, so
            # scatter order never matters
            toks[i] = pr.first_token
            if seen_rows is not None:
                seen_rows[i, prompt] = True
                seen_rows[i, pr.first_token] = True
        self._cache = _paged.set_block_tables(self._cache, self._tables)
        self._tables_dirty = False
        self._dispatches += 1
        kv_dev = {name: jnp.asarray(b) for name, b in stacked.items()}
        blk_dev = jnp.asarray(blk)
        self._mem_register(
            f"serve/prefill_primed/b{bucket}r{rp}",
            _scatter_primed_blocks,
            (self._cache, kv_dev, blk_dev),
            donated=self._cache,
        )
        self._cache = _scatter_primed_blocks(self._cache, kv_dev, blk_dev)
        self._dispatches += 1
        if seen_rows is not None:
            rows_pad = np.asarray(
                list(rows) + [rows[0]] * (rp - n), np.int32)
            self._seen = self._seen.at[jnp.asarray(rows_pad)].set(
                jnp.asarray(seen_rows))
            self._dispatches += 1
        return toks  # first tokens are host-known: no sync on this path

    def _warm_wave(self, key, group, rows) -> np.ndarray:
        """Admit rows whose prompt prefix is cached: land the prefix K/V
        and prefill ONLY the suffix, one donated program per (prefix
        length, suffix bucket) group — the shared-system-prompt fast
        path the prefix cache exists for."""
        pre_len, sbucket, fbucket = key
        # _plan_wave guarantees the suffix bucket fits the row past the
        # scattered prefix; a violation here would clamp the cache write
        # and corrupt the prefix K/V silently
        assert pre_len + sbucket <= self._max_len, (pre_len, sbucket)
        n = len(group)
        rp = _pad_wave(n, self._b)
        suffixes = np.full((rp, sbucket), self._pad, np.int32)
        last = np.zeros(rp, np.int32)
        fullp = plens = None
        if self._seen is not None:
            fullp = np.full((rp, fbucket), self._pad, np.int32)
            plens = np.zeros(rp, np.int32)
        kv_rows = []
        for i in range(rp):
            _rid, prompt, _budget, _pr, kv = group[i if i < n else 0]
            suffix = prompt[pre_len:]
            suffixes[i, :suffix.size] = suffix
            last[i] = suffix.size - 1
            if fullp is not None:
                fullp[i, :prompt.size] = prompt
                plens[i] = prompt.size
            kv_rows.append(kv)
        kv_stack = {
            name: jnp.stack([k[name] for k in kv_rows])
            for name in kv_rows[0]
        }
        valid = None
        if fullp is not None:
            valid = jnp.asarray(np.arange(fbucket)[None, :] < plens[:, None])
            fullp = jnp.asarray(fullp)
        rng = None
        if self._sampling["temperature"] != 0.0:
            self._rng, rng = jax.random.split(self._rng)
        tmpl = self._row_template(rp)
        suffixes_dev = jnp.asarray(suffixes)
        last_dev = jnp.asarray(last)
        self._mem_register(
            f"serve/prefill_warm/p{pre_len}s{sbucket}r{rp}",
            functools.partial(_prefill_suffix, self._decode_model,
                              **self._sampling),
            (tmpl, self._params, kv_stack, suffixes_dev, last_dev, fullp,
             valid, rng),
            donated=tmpl,
        )
        row_cache, tok, row_seen = _prefill_suffix(
            self._decode_model, tmpl, self._params,
            kv_stack, suffixes_dev, last_dev, fullp,
            valid, rng, **self._sampling,
        )
        self._dispatches += 2  # the per-wave kv stack + the fused prefill
        rows_pad = np.asarray(rows + [rows[0]] * (rp - n), np.int32)
        rows_dev = jnp.asarray(rows_pad)
        self._cache = _scatter_rows(self._cache, row_cache, rows_dev)
        self._dispatches += 1
        if row_seen is not None:
            if rp > n:
                sel = np.arange(rp)
                sel[n:] = 0
                row_seen = row_seen[jnp.asarray(sel)]
            self._seen = self._seen.at[rows_dev].set(row_seen)
            self._dispatches += 1
        tok_np = _fetch(tok)
        self._syncs += 1
        return tok_np

    # -- prefill/decode role split -------------------------------------------
    def prime(self, prompt, max_new_tokens: int,
              trace: Optional[str] = None) -> PrimedRequest:
        """Run ONLY the prefill for one request and return the hand-off
        payload (host K/V + pending first token) for a decode replica's
        `submit_primed()` — the prefill half of the role split. Touches
        no decode row and no queue, so a prefill-role replica can serve
        long-prompt admissions without ever stalling a decode scan."""
        if self._role == "decode":
            raise RuntimeError("decode-only replica cannot prime")
        t_prime = time.perf_counter()
        prompt = self._check_request(prompt, max_new_tokens)
        bucket = next(b for b in self._buckets if b >= prompt.size)
        prompts = np.full((1, bucket), self._pad, np.int32)
        prompts[0, :prompt.size] = prompt
        last = np.asarray([prompt.size - 1], np.int32)
        valid = None
        if self._seen is not None:
            valid = jnp.asarray(np.arange(bucket)[None, :] < prompt.size)
        rng = None
        if self._sampling["temperature"] != 0.0:
            self._rng, rng = jax.random.split(self._rng)
        row_cache, tok, _ = _prefill_rows(
            self._decode_model, self._row_template(1), self._params,
            jnp.asarray(prompts), jnp.asarray(last), valid, rng,
            **self._sampling,
        )
        self._dispatches += 1
        if self._prefix is not None and not self._paged:
            # the paged trie holds POOL BLOCK IDS; prime() runs on the
            # dense row layout (the hand-off is layout-agnostic), so
            # its segments have no block to adopt — only locally
            # admitted prompts seed the paged trie
            self._prefix.insert(prompts[0, :prompt.size], row_cache, 0)
        kv = {}
        for path, leaf in jax.tree_util.tree_leaves_with_path(row_cache):
            if is_index_leaf(path):
                continue
            kv[leaf_name(path)] = leaf[0, :prompt.size]
        kv_np, tok_np = _fetch((kv, tok))
        self._syncs += 1
        if trace is not None and _trace.active():
            # the prefill half of the primed hand-off: the decode
            # replica's serve/queued(primed=True) is the other half
            _trace.event("serve/prime", trace=trace,
                         dur=time.perf_counter() - t_prime,
                         prompt_tokens=int(prompt.size))
        return PrimedRequest(
            prompt=prompt.astype(np.int32),
            first_token=int(tok_np[0]),
            max_new_tokens=int(max_new_tokens),
            kv=kv_np,
        )

    def _primed_wave(self, bucket: int, group, rows) -> np.ndarray:
        """Admit rows primed on another replica: stack the shipped host
        K/V, one donated multi-row scatter, zero model flops here — the
        decode scan never waits behind a long-prompt prefill."""
        if self._paged:
            return self._primed_paged_wave(bucket, group, rows)
        n = len(group)
        rp = _pad_wave(n, self._b)
        rows_pad = np.asarray(rows + [rows[0]] * (rp - n), np.int32)
        sample = group[0][3].kv
        stacked = {
            name: np.zeros((rp, bucket) + arr.shape[1:], arr.dtype)
            for name, arr in sample.items()
        }
        toks = np.zeros(rp, np.int64)
        seen_rows = (
            np.zeros((rp, self._vocab), bool)
            if self._seen is not None else None
        )
        for i in range(rp):
            _rid, prompt, _budget, pr, _x = group[i if i < n else 0]
            for name, arr in pr.kv.items():
                stacked[name][i, :arr.shape[0]] = arr
            toks[i] = pr.first_token
            if seen_rows is not None:
                # rebuild the presence mask from ids — cheaper to recompute
                # than to ship a [vocab] row across processes
                seen_rows[i, prompt] = True
                seen_rows[i, pr.first_token] = True
        kv_dev = {name: jnp.asarray(b) for name, b in stacked.items()}
        rows_dev = jnp.asarray(rows_pad)
        self._mem_register(
            f"serve/prefill_primed/b{bucket}r{rp}",
            _scatter_primed_rows,
            (self._cache, kv_dev, rows_dev),
            donated=self._cache,
        )
        self._cache = _scatter_primed_rows(self._cache, kv_dev, rows_dev)
        self._dispatches += 1
        if seen_rows is not None:
            self._seen = self._seen.at[rows_dev].set(jnp.asarray(seen_rows))
            self._dispatches += 1
        return toks  # first tokens are host-known: no sync on this path


class SpeculativeContinuousBatcher(_BatcherBase):
    """Continuous batching accelerated by a draft model — the two serving
    levers composed: every round, the draft proposes `num_draft` tokens
    per row and ONE target forward verifies all of them
    (inference/speculative.py's batch-generic round, per-row acceptance),
    while finished rows admit queued requests mid-flight exactly like
    `ContinuousBatcher` — including the bucketed wave admission: both
    caches prefill every freed row of a bucket in one call each and land
    with one multi-row scatter per cache.

    temperature == 0 (default): deterministic rounds — each request's
    output equals its solo greedy `generate(model, params, prompt)` run.
    temperature > 0: speculative SAMPLING rounds (the Leviathan
    acceptance, inference/speculative.py) — committed tokens are
    distributed exactly as target-model sampling at that temperature per
    request, with draw values batch-dependent (rows share the key
    stream). Per-round commits vary between 1 and num_draft+1 tokens per
    row with draft quality; `stats()` reports the realized tokens/round
    and draft acceptance rate.
    """

    _metrics_prefix = "serving/speculative"

    def __init__(
        self,
        model,
        draft_model,
        params,
        draft_params,
        batch_size: int,
        max_len: int,
        num_draft: int = 4,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        rng: Optional[jax.Array] = None,
        prompt_buckets: Optional[tuple] = None,
    ):
        if num_draft < 1:
            raise ValueError(f"num_draft must be >= 1, got {num_draft}")
        super().__init__(model, params, batch_size, max_len, eos_id,
                         pad_id, rng, prompt_buckets)
        from tfde_tpu.inference.speculative import (
            _spec_round,
            _spec_round_sampled,
        )

        self._round = _spec_round
        self._round_sampled = _spec_round_sampled
        self._temperature = float(temperature)
        self._draft = draft_model
        self._tgt = _decode_clone(model)
        self._drf = _decode_clone(draft_model)
        self._dparams = draft_params
        self._nd = int(num_draft)
        # the speculative cache invariant: each round feeds at most
        # num_draft+1 tokens past a row's committed count before the
        # rewind (inference/speculative.py cache sizing)
        self._cache_len = self._max_len + self._nd + 1
        self._tgt_cache = init_cache(model, batch_size, self._cache_len)
        self._drf_cache = init_cache(draft_model, batch_size,
                                     self._cache_len)
        # the ledger tracks the TARGET slab (the draft cache is a cost
        # of speculation, not serving capacity)
        self._init_capacity(self._tgt_cache,
                            cells_per_row=self._cache_len)
        self._tgt_templates: dict = {}
        self._drf_templates: dict = {}
        self._round_tokens = 0   # tokens produced by speculative rounds
        self._draft_proposed = 0  # num_draft per active row per round
        self._draft_accepted = 0  # committed beyond the guaranteed token

    def stats(self) -> dict:
        """Speculation effectiveness: tokens/round is per ROW per round
        (1.0 = no draft ever accepted, num_draft+1 = perfect draft);
        acceptance_rate is the fraction of proposed draft tokens the
        target committed. dispatches/syncs mirror ContinuousBatcher's
        host-overhead accounting."""
        return {
            "rounds": self._rounds,
            "generated": self._generated,
            "tokens_per_round": (
                self._round_tokens / max(self._rounds * self._b, 1)
            ),
            "acceptance_rate": (
                self._draft_accepted / max(self._draft_proposed, 1)
            ),
            "dispatches": self._dispatches,
            "syncs": self._syncs,
        }

    def _validate_submit(self, prompt, max_new_tokens) -> None:
        super()._validate_submit(prompt, max_new_tokens)
        validate_budget(self._draft, int(prompt.size), max_new_tokens)

    def _template(self, shapes: dict, model, rp: int):
        """Fresh zero rows for the donated prefill, from shapes cached
        per wave size (see ContinuousBatcher._row_template)."""
        if rp not in shapes:
            shapes[rp] = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                init_cache(model, rp, self._cache_len),
            )
        self._dispatches += 1
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            shapes[rp])

    def _prefill_wave(self, prompts, last, rows, plens, n) -> np.ndarray:
        rp = prompts.shape[0]
        prompts_dev = jnp.asarray(prompts)
        last_dev = jnp.asarray(last)
        rng = None
        if self._temperature > 0.0:
            self._rng, rng = jax.random.split(self._rng)
        tgt_rows, tok, _ = _prefill_rows(
            self._tgt, self._template(self._tgt_templates, self._model, rp),
            self._params, prompts_dev, last_dev, None, rng,
            temperature=self._temperature, top_k=None, top_p=None,
            min_p=None, repetition_penalty=1.0,
        )
        # the draft prefill only needs its cache filled; its sampled token
        # is discarded (greedy argmax — no rng consumed)
        drf_rows, _, _ = _prefill_rows(
            self._drf, self._template(self._drf_templates, self._draft, rp),
            self._dparams, prompts_dev, last_dev, None, None,
            temperature=0.0, top_k=None, top_p=None, min_p=None,
            repetition_penalty=1.0,
        )
        self._dispatches += 2
        rows_dev = jnp.asarray(rows)
        self._tgt_cache = _scatter_rows(self._tgt_cache, tgt_rows, rows_dev)
        self._drf_cache = _scatter_rows(self._drf_cache, drf_rows, rows_dev)
        self._dispatches += 2
        tok_np = _fetch(tok)
        self._syncs += 1
        return tok_np

    def step(self) -> list:
        """Admit, then run ONE speculative round for the whole batch;
        returns the requests that finished on it."""
        with span("serving/admit"):
            finished = self._admit()
        active = [r for r in range(self._b) if self._req[r] is not None]
        if not active:
            self._publish_stats()
            return finished
        self._rounds += 1
        t0 = time.perf_counter()
        with span("serving/decode"):
            # per-round rewind is unconditional: acceptance lengths diverge
            # every round (host ints/np arrays — own buffer per index leaf,
            # across BOTH donated caches)
            committed = self._committed.astype(np.int32)
            self._tgt_cache = _set_index_counters(self._tgt_cache, committed)
            self._drf_cache = _set_index_counters(self._drf_cache, committed)
            self._dispatches += 2
            if self._temperature > 0.0:
                self._rng, sub = jax.random.split(self._rng)
                (self._tgt_cache, self._drf_cache, round_toks, n_new,
                 _pending, _rng_out) = self._round_sampled(
                    self._tgt, self._drf, self._tgt_cache, self._drf_cache,
                    self._params, self._dparams,
                    jnp.asarray(self._tok, jnp.int32), sub, self._nd,
                    self._pad, self._temperature,
                )
            else:
                (self._tgt_cache, self._drf_cache, round_toks, n_new,
                 _pending) = self._round(
                    self._tgt, self._drf, self._tgt_cache, self._drf_cache,
                    self._params, self._dparams,
                    jnp.asarray(self._tok, jnp.int32), self._nd, self._pad,
                )
            self._dispatches += 1
            round_np, n_np = _fetch((round_toks, n_new))
            self._syncs += 1
        traced = (
            [self._trace_ids[rid] for r in active
             if (rid := self._req[r]) in self._trace_ids]
            if self._trace_ids else []
        )
        self._profiler_round(traced)
        n_emitted = 0
        for r in active:
            toks = round_np[r, : int(n_np[r])].tolist()
            taken = 0
            for t in toks:
                if self._req[r] is None:
                    break  # row finished mid-round; overshoot discarded
                self._round_tokens += 1
                finished.extend(self._take_token(r, int(t)))
                taken += 1
            n_emitted += taken
            # acceptance bookkeeping: each round proposes num_draft per
            # active row; a row's commits beyond the guaranteed target
            # token are accepted draft proposals (capped by num_draft —
            # the +1'th commit is the bonus token, not a draft)
            self._draft_proposed += self._nd
            self._draft_accepted += min(max(taken - 1, 0), self._nd)
            if self._req[r] is not None:
                # row still active: tok_last + accepted tokens are now in
                # both caches (the pending one stays unfed) — the
                # generate_speculative commit bookkeeping
                self._committed[r] += taken
        dt = time.perf_counter() - t0
        if traced:
            _trace.event("serve/decode_round", traces=traced, dur=dt,
                         depth=self._nd, rows=len(active),
                         emitted=n_emitted)
        if n_emitted:
            metrics.default_registry().histogram(
                "serving/ms_per_token"
            ).observe(dt * 1e3 / n_emitted)
            self._admission.note_drain(n_emitted, dt)
        self._publish_stats()
        return finished
