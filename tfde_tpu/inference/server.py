"""Continuous batching — the serving loop that keeps every batch row busy.

`generate` (inference/decode.py) serves one batch to completion: rows that
finish early ride along as padding until the slowest row ends, and new
requests wait for the whole batch. A serving deployment wants the modern
alternative: a FIXED decode batch where a finished row is immediately
re-used for the next queued request while the other rows keep decoding —
continuous batching (the vLLM/Orca scheduling idea, re-built on this
framework's primitives).

What makes it cheap here: the per-row KV-cache machinery built for
batched speculative decoding (models/transformer.py `_decode_attention`
vector branch + per-row `position_index`) already lets every batch row
sit at a DIFFERENT sequence position with its own validity horizon.
Admission is then per-row cache surgery:

- one compiled DECODE tick serves the whole batch ([B, 1] tokens,
  per-row [B] cache indices — stale K/V beyond a row's index is
  unreachable, so re-using a slot needs no cache clearing);
- one compiled PREFILL per distinct prompt length runs the new request
  on a single-row cache, whose K/V leaves are scattered into the big
  cache at the freed row (`.at[row].set`), and whose last-position
  logits seed the row's first token immediately;
- sampling, EOS, and budget bookkeeping are per-row host state.

Greedy determinism: each request's output equals a solo
`generate(model, params, prompt)` run token for token regardless of what
shares the batch (tests/test_server.py asserts it across staggered
admissions). Temperature>0 draws ride a shared key stream —
distributionally correct per request, draw values batch-dependent.

Prompt-length compiles: prompts are right-padded to the smallest of
`prompt_buckets` that fits (powers of two up to max_len by default), so
the prefill compiles once per BUCKET, not per length — the first-token
logits are read at the true prompt's last position, and the pre-tick
index rewind makes the pad K/V unreachable.
"""

from __future__ import annotations

import collections
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.inference.decode import (
    _decode_clone,
    init_cache,
    sample_logits,
    validate_budget,
)
from tfde_tpu.inference.speculative import _set_index_counters
from tfde_tpu.observability import metrics
from tfde_tpu.observability.spans import span


@functools.partial(jax.jit, static_argnames=("model",), donate_argnums=(1,))
def _decode_tick(model, cache, params, toks):
    """One decode step for the whole batch: [B] tokens in, fp32 [B, V]
    last-position logits out. Per-row cache indices advance by 1."""
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, toks[:, None], train=False,
        mutable=["cache"],
    )
    return mutated["cache"], logits[:, -1].astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("model",))
def _prefill_row(model, row_cache, params, prompt, last):
    """Prefill a single-row cache with a [1, Pbucket] (possibly right-
    padded) prompt; returns the filled cache and fp32 [1, V] logits at
    position `last` — the true prompt's final position, so bucketing
    never changes the first sampled token. Compiled per BUCKET length.

    Pad correctness rides the per-row index machinery: the pad tokens'
    K/V land beyond the row's committed count, which the pre-tick rewind
    sets to the TRUE prompt length — stale entries are unreachable, the
    same invariant speculative rewinds rely on."""
    logits, mutated = model.apply(
        {"params": params, "cache": row_cache}, prompt, train=False,
        mutable=["cache"],
    )
    return mutated["cache"], logits[:, last].astype(jnp.float32)


def _normalize_buckets(buckets, max_len: int) -> tuple:
    """Sorted prefill bucket lengths; default powers of two up to
    max_len. Every prompt pads up to the smallest bucket that fits."""
    if buckets is None:
        buckets, b = [], 8
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
    # clamp to max_len: a larger bucket would pad past the row cache and
    # fail at ADMISSION (after the request left the queue), not here
    out = tuple(sorted({min(int(b), max_len) for b in buckets}))
    if not out or out[-1] < max_len:
        raise ValueError(
            f"prompt_buckets must cover max_len {max_len}; got {out}"
        )
    return out


def _bucketed(prompt: np.ndarray, buckets: tuple, pad_id: int):
    """(padded [1, bucket] int32 prompt, true-last-position index)."""
    p = prompt.size
    bucket = next(b for b in buckets if b >= p)
    padded = np.full((1, bucket), pad_id, np.int32)
    padded[0, :p] = prompt
    return jnp.asarray(padded), p - 1


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_row(cache, row_cache, row):
    """Write a single-row cache's K/V leaves into batch row `row` — the
    batch cache is donated, so the update lowers in place instead of
    copying every [B, max_len, ...] leaf per admission. Index counters
    pass through (they are rewound wholesale before the next tick)."""

    def merge(path, big, small):
        name = str(getattr(path[-1], "key", path[-1]))
        if name in ("cache_index", "position_index"):
            return big
        return big.at[row].set(small[0])

    return jax.tree_util.tree_map_with_path(merge, cache, row_cache)


class ContinuousBatcher:
    """Fixed-batch continuous serving loop over a causal LM.

    model/params: a decode-capable model (GPT family) and its params.
    batch_size: resident decode rows. max_len: per-row cache budget
    (prompt + generated must fit). The sampling config is fixed per
    batcher, as for `generate`.

    Usage::

        srv = ContinuousBatcher(model, params, batch_size=4, max_len=256)
        rid = srv.submit(prompt_1d, max_new_tokens=64)
        while not srv.idle:
            for req_id, tokens in srv.step():
                ...   # finished requests, completion order

    `step()` admits queued requests into free rows (per-row prefill) and
    runs ONE decode tick for the batch; it returns the requests finishing
    on that call. `run()` drains everything.

    Invariant per active row r (the speculative-decoding contract): the
    cache holds K/V for exactly `committed[r]` tokens and `tok[r]` is the
    last generated-but-unfed token.
    """

    def __init__(
        self,
        model,
        params,
        batch_size: int,
        max_len: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: float = 1.0,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        rng: Optional[jax.Array] = None,
        prompt_buckets: Optional[tuple] = None,
    ):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if repetition_penalty <= 0.0:
            raise ValueError(
                f"repetition_penalty must be > 0 (1.0 = off), got "
                f"{repetition_penalty}"
            )
        self._buckets = _normalize_buckets(prompt_buckets, max_len)
        self._decode_model = _decode_clone(model)
        self._model = model
        self._params = params
        self._b = batch_size
        self._max_len = int(max_len)
        self._sample = functools.partial(
            sample_logits, temperature=temperature, top_k=top_k,
            top_p=top_p, min_p=min_p,
            repetition_penalty=repetition_penalty,
        )
        # presence mask for the repetition penalty (per row, prompt ids
        # included — the generate() convention); lives ON DEVICE and is
        # updated with .at scatters, so steady-state ticks ship no
        # [B, vocab] host copies
        self._seen = (
            jnp.zeros((batch_size, model.vocab_size), bool)
            if repetition_penalty != 1.0 else None
        )
        self._vocab = model.vocab_size
        self._eos = eos_id
        self._pad = pad_id
        self._rng = rng if rng is not None else jax.random.key(0)

        self._cache = init_cache(model, batch_size, self._max_len)
        # zero single-row cache template, built once: _prefill_row does
        # not donate its cache argument, so the template survives reuse
        self._row_template = init_cache(model, 1, self._max_len)
        self._req = [None] * batch_size          # request id or None
        self._out = [[] for _ in range(batch_size)]
        self._budget = np.zeros(batch_size, np.int64)
        self._committed = np.zeros(batch_size, np.int64)
        self._tok = np.full(batch_size, pad_id, np.int64)
        self._queue: collections.deque = collections.deque()
        self._next_id = 0
        self._rounds = 0         # decode ticks run
        self._generated = 0      # every delivered token (incl. prefill 1st)
        # device indices match self._committed only after a rewind; any
        # admission or completion desyncs them until the next tick rewinds
        self._indices_dirty = True

    # -- public -------------------------------------------------------------
    @property
    def idle(self) -> bool:
        return not self._queue and all(r is None for r in self._req)

    @property
    def free_rows(self) -> int:
        return sum(r is None for r in self._req)

    def stats(self) -> dict:
        """Serving throughput: decode rounds run, tokens delivered, and
        tokens/round = generated / rounds — effectively the mean occupied
        rows per tick (each occupied row yields one token; prefill first
        tokens ride the admitting round's count)."""
        return {
            "rounds": self._rounds,
            "generated": self._generated,
            "tokens_per_round": self._generated / max(self._rounds, 1),
        }

    def _publish_stats(self, prefix: str = "serving/batcher") -> None:
        """Mirror stats() into the metric registry so serving throughput
        rides the /metrics and JSONL exposition paths."""
        reg = metrics.default_registry()
        for k, v in self.stats().items():
            reg.gauge(f"{prefix}/{k}").set(v)
        reg.gauge(f"{prefix}/queue_depth").set(len(self._queue))
        reg.gauge(f"{prefix}/free_rows").set(self.free_rows)

    def serve_metrics(self, port: int = 0):
        """Start a /metrics endpoint next to this batcher (exposition.py);
        returns the MetricsServer (read `.port` back when port=0)."""
        from tfde_tpu.observability.exposition import serve_metrics

        return serve_metrics(port=port)

    def submit(self, prompt, max_new_tokens: int) -> int:
        """Queue a request; returns its id. prompt: 1-D int token ids."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if self._seen is not None and (
                prompt.min() < 0 or prompt.max() >= self._vocab):
            # queue-time, not admission-time (the _normalize_buckets rule):
            # jnp .at scatters DROP out-of-bounds updates silently, so an
            # over-vocab id would simply go un-penalized and a negative id
            # would mark the wrong entry via wraparound — no crash, just
            # quietly wrong sampling; refuse here instead
            raise ValueError(
                f"prompt ids must lie in [0, {self._vocab}) when "
                f"repetition_penalty is on; got "
                f"[{int(prompt.min())}, {int(prompt.max())}]"
            )
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        validate_budget(self._model, int(prompt.size), max_new_tokens)
        if prompt.size + max_new_tokens > self._max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the batcher's max_len "
                f"{self._max_len}"
            )
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, prompt, int(max_new_tokens)))
        return rid

    def step(self) -> list:
        """Admit into free rows, run one decode tick; returns
        [(request_id, tokens 1-D np.int32), ...] that finished now."""
        with span("serving/admit"):
            finished = self._admit()
        active = [r for r in range(self._b) if self._req[r] is not None]
        if not active:
            self._publish_stats()
            return finished

        with span("serving/decode"):
            if self._indices_dirty:
                # host values, not a shared jnp array: every index leaf gets
                # its own buffer (the donated-cache aliasing rule). Steady
                # state (no admissions/completions) skips this: the device
                # indices advance by exactly 1 per tick, matching _committed.
                self._cache = _set_index_counters(
                    self._cache, self._committed.astype(np.int32)
                )
                self._indices_dirty = False
            self._cache, logits = _decode_tick(
                self._decode_model, self._cache, self._params,
                jnp.asarray(self._tok, jnp.int32),
            )
            self._rng, sub = jax.random.split(self._rng)
            toks = np.asarray(self._sample(logits, sub, seen=self._seen))
        self._rounds += 1
        if self._seen is not None:
            act = np.asarray(active)
            self._seen = self._seen.at[act, toks[act]].set(True)
        for r in active:
            # feeding tok[r] committed it; the new sample is now pending
            self._committed[r] += 1
            finished.extend(self._take_token(r, int(toks[r])))
        self._publish_stats()
        return finished

    def run(self) -> list:
        """Step until idle; returns every completion in finish order."""
        done = []
        while not self.idle:
            done.extend(self.step())
        return done

    # -- internals ----------------------------------------------------------
    def _take_token(self, r: int, t: int) -> list:
        """Record a sampled token for row r; frees the row on completion."""
        self._out[r].append(t)
        self._budget[r] -= 1
        self._tok[r] = t
        self._generated += 1
        if self._budget[r] <= 0 or (self._eos is not None and t == self._eos):
            done = (self._req[r], np.asarray(self._out[r], np.int32))
            self._req[r] = None
            self._out[r] = []
            self._committed[r] = 0
            self._tok[r] = self._pad
            if self._seen is not None:
                self._seen = self._seen.at[r].set(False)
            self._indices_dirty = True
            return [done]
        return []

    def _admit(self) -> list:
        """Fill free rows from the queue. The prefill samples the row's
        first token immediately (generate's prefill contract), so every
        active row uniformly holds one pending token afterwards. A
        request finishing on its first token (budget 1 / instant EOS)
        frees the row for the next queued request in the same call."""
        finished = []
        progress = True
        while progress and self._queue:
            progress = False
            for r in range(self._b):
                if not self._queue or self._req[r] is not None:
                    continue
                rid, prompt, budget = self._queue.popleft()
                ids, last = _bucketed(prompt, self._buckets, self._pad)
                with span("serving/prefill"):
                    row_cache, logits = _prefill_row(
                        self._decode_model, self._row_template, self._params,
                        ids, last,
                    )
                self._cache = _scatter_row(
                    self._cache, row_cache, jnp.int32(r)
                )
                self._indices_dirty = True
                if self._seen is not None:
                    # row r is all-False by invariant (_take_token clears
                    # on completion; init starts zeroed) — only the prompt
                    # scatter is needed
                    self._seen = self._seen.at[
                        r, jnp.asarray(prompt)
                    ].set(True)
                self._rng, sub = jax.random.split(self._rng)
                t = int(np.asarray(self._sample(
                    logits, sub,
                    seen=(None if self._seen is None
                          else self._seen[r:r + 1]),
                ))[0])
                if self._seen is not None:
                    self._seen = self._seen.at[r, t].set(True)
                self._req[r] = rid
                self._out[r] = []
                self._budget[r] = budget
                self._committed[r] = prompt.size
                finished.extend(self._take_token(r, t))
                progress = True
        return finished


class SpeculativeContinuousBatcher:
    """Continuous batching accelerated by a draft model — the two serving
    levers composed: every round, the draft proposes `num_draft` tokens
    per row and ONE target forward verifies all of them
    (inference/speculative.py's batch-generic round, per-row acceptance),
    while finished rows admit queued requests mid-flight exactly like
    `ContinuousBatcher`.

    temperature == 0 (default): deterministic rounds — each request's
    output equals its solo greedy `generate(model, params, prompt)` run.
    temperature > 0: speculative SAMPLING rounds (the Leviathan
    acceptance, inference/speculative.py) — committed tokens are
    distributed exactly as target-model sampling at that temperature per
    request, with draw values batch-dependent (rows share the key
    stream). Per-round commits vary between 1 and num_draft+1 tokens per
    row with draft quality; `stats()` reports the realized tokens/round
    and draft acceptance rate.
    """

    def __init__(
        self,
        model,
        draft_model,
        params,
        draft_params,
        batch_size: int,
        max_len: int,
        num_draft: int = 4,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        rng: Optional[jax.Array] = None,
        prompt_buckets: Optional[tuple] = None,
    ):
        self._buckets = _normalize_buckets(prompt_buckets, max_len)
        from tfde_tpu.inference.speculative import (
            _spec_round,
            _spec_round_sampled,
        )

        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if num_draft < 1:
            raise ValueError(f"num_draft must be >= 1, got {num_draft}")
        self._round = _spec_round
        self._round_sampled = _spec_round_sampled
        self._temperature = float(temperature)
        self._rng = rng if rng is not None else jax.random.key(0)
        self._model = model
        self._draft = draft_model
        self._tgt = _decode_clone(model)
        self._drf = _decode_clone(draft_model)
        self._params = params
        self._dparams = draft_params
        self._b = batch_size
        self._max_len = int(max_len)
        self._nd = int(num_draft)
        self._eos = eos_id
        self._pad = pad_id
        # the speculative cache invariant: each round feeds at most
        # num_draft+1 tokens past a row's committed count before the
        # rewind (inference/speculative.py cache sizing)
        cache_len = self._max_len + self._nd + 1
        self._tgt_cache = init_cache(model, batch_size, cache_len)
        self._drf_cache = init_cache(draft_model, batch_size, cache_len)
        self._tgt_row = init_cache(model, 1, cache_len)
        self._drf_row = init_cache(draft_model, 1, cache_len)

        self._req = [None] * batch_size
        self._out = [[] for _ in range(batch_size)]
        self._budget = np.zeros(batch_size, np.int64)
        self._committed = np.zeros(batch_size, np.int64)
        self._tok = np.full(batch_size, pad_id, np.int64)
        self._queue: collections.deque = collections.deque()
        self._next_id = 0
        self._rounds = 0
        self._generated = 0      # every delivered token (incl. prefill 1st)
        self._round_tokens = 0   # tokens produced by speculative rounds
        self._draft_proposed = 0  # num_draft per active row per round
        self._draft_accepted = 0  # committed beyond the guaranteed token

    @property
    def idle(self) -> bool:
        return not self._queue and all(r is None for r in self._req)

    def stats(self) -> dict:
        """Speculation effectiveness: tokens/round is per ROW per round
        (1.0 = no draft ever accepted, num_draft+1 = perfect draft);
        acceptance_rate is the fraction of proposed draft tokens the
        target committed."""
        return {
            "rounds": self._rounds,
            "generated": self._generated,
            "tokens_per_round": (
                self._round_tokens / max(self._rounds * self._b, 1)
            ),
            "acceptance_rate": (
                self._draft_accepted / max(self._draft_proposed, 1)
            ),
        }

    def _publish_stats(self, prefix: str = "serving/speculative") -> None:
        reg = metrics.default_registry()
        for k, v in self.stats().items():
            reg.gauge(f"{prefix}/{k}").set(v)
        reg.gauge(f"{prefix}/queue_depth").set(len(self._queue))

    def serve_metrics(self, port: int = 0):
        """Start a /metrics endpoint next to this batcher (exposition.py);
        returns the MetricsServer (read `.port` back when port=0)."""
        from tfde_tpu.observability.exposition import serve_metrics

        return serve_metrics(port=port)

    def submit(self, prompt, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        validate_budget(self._model, int(prompt.size), max_new_tokens)
        validate_budget(self._draft, int(prompt.size), max_new_tokens)
        if prompt.size + max_new_tokens > self._max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the batcher's max_len "
                f"{self._max_len}"
            )
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, prompt, int(max_new_tokens)))
        return rid

    def _take_token(self, r: int, t: int) -> list:
        self._out[r].append(t)
        self._budget[r] -= 1
        self._tok[r] = t
        self._generated += 1
        if self._budget[r] <= 0 or (self._eos is not None and t == self._eos):
            done = (self._req[r], np.asarray(self._out[r], np.int32))
            self._req[r] = None
            self._out[r] = []
            self._committed[r] = 0
            self._tok[r] = self._pad
            return [done]
        return []

    def _admit(self) -> list:
        finished = []
        progress = True
        while progress and self._queue:
            progress = False
            for r in range(self._b):
                if not self._queue or self._req[r] is not None:
                    continue
                rid, prompt, budget = self._queue.popleft()
                ids, last = _bucketed(prompt, self._buckets, self._pad)
                with span("serving/prefill"):
                    tgt_row, logits = _prefill_row(
                        self._tgt, self._tgt_row, self._params, ids, last
                    )
                    drf_row, _ = _prefill_row(
                        self._drf, self._drf_row, self._dparams, ids, last
                    )
                self._tgt_cache = _scatter_row(
                    self._tgt_cache, tgt_row, jnp.int32(r)
                )
                self._drf_cache = _scatter_row(
                    self._drf_cache, drf_row, jnp.int32(r)
                )
                if self._temperature > 0.0:
                    self._rng, sub = jax.random.split(self._rng)
                    t = int(np.asarray(sample_logits(
                        logits, sub, temperature=self._temperature
                    ))[0])
                else:
                    t = int(np.asarray(jnp.argmax(logits, axis=-1))[0])
                self._req[r] = rid
                self._out[r] = []
                self._budget[r] = budget
                self._committed[r] = prompt.size
                finished.extend(self._take_token(r, t))
                progress = True
        return finished

    def step(self) -> list:
        """Admit, then run ONE speculative round for the whole batch;
        returns the requests that finished on it."""
        with span("serving/admit"):
            finished = self._admit()
        active = [r for r in range(self._b) if self._req[r] is not None]
        if not active:
            self._publish_stats()
            return finished
        self._rounds += 1
        with span("serving/decode"):
            # per-round rewind is unconditional: acceptance lengths diverge
            # every round (host ints/np arrays — own buffer per index leaf,
            # across BOTH donated caches)
            committed = self._committed.astype(np.int32)
            self._tgt_cache = _set_index_counters(self._tgt_cache, committed)
            self._drf_cache = _set_index_counters(self._drf_cache, committed)
            if self._temperature > 0.0:
                self._rng, sub = jax.random.split(self._rng)
                (self._tgt_cache, self._drf_cache, round_toks, n_new,
                 _pending, _rng_out) = self._round_sampled(
                    self._tgt, self._drf, self._tgt_cache, self._drf_cache,
                    self._params, self._dparams,
                    jnp.asarray(self._tok, jnp.int32), sub, self._nd,
                    self._pad, self._temperature,
                )
            else:
                (self._tgt_cache, self._drf_cache, round_toks, n_new,
                 _pending) = self._round(
                    self._tgt, self._drf, self._tgt_cache, self._drf_cache,
                    self._params, self._dparams,
                    jnp.asarray(self._tok, jnp.int32), self._nd, self._pad,
                )
            round_np = np.asarray(round_toks)
            n_np = np.asarray(n_new)
        for r in active:
            toks = round_np[r, : int(n_np[r])].tolist()
            taken = 0
            for t in toks:
                if self._req[r] is None:
                    break  # row finished mid-round; overshoot discarded
                self._round_tokens += 1
                finished.extend(self._take_token(r, int(t)))
                taken += 1
            # acceptance bookkeeping: each round proposes num_draft per
            # active row; a row's commits beyond the guaranteed target
            # token are accepted draft proposals (capped by num_draft —
            # the +1'th commit is the bonus token, not a draft)
            self._draft_proposed += self._nd
            self._draft_accepted += min(max(taken - 1, 0), self._nd)
            if self._req[r] is not None:
                # row still active: tok_last + accepted tokens are now in
                # both caches (the pending one stays unfed) — the
                # generate_speculative commit bookkeeping
                self._committed[r] += taken
        self._publish_stats()
        return finished

    def run(self) -> list:
        done = []
        while not self.idle:
            done.extend(self.step())
        return done
