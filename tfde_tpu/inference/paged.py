"""Paged KV: one block-granular pool shared by the prefix trie and rows.

The dense serving cache allocates every row `max_len` cells up front, so
`kv/waste_frac` (observability/capacity.py) reports everything a short
request never touches as burned HBM, and the prefix trie keeps a SECOND
copy of every cached prefix outside the slab. This module replaces both
with the vLLM-style layout the capacity ledger was built to motivate
(ROADMAP item 1):

- **BlockPool** — the host-side allocator for the physical pool the
  model owns as `pool_key`/`pool_value` cache variables ([num_blocks,
  block, kv_heads, head_dim] per layer; models/transformer.py
  `_paged_attention`). Blocks are refcounted so one physical block can
  back the trie AND any number of active rows at once; block 0 is the
  pinned null block (unallocated table slots point there, junk writes
  land there). Free-list state is lock-guarded (`_lock` — the batcher's
  step loop writes while HTTP handler threads read `stats()`; listed in
  tools/tfdelint.py LOCKED_CLASSES).
- **PagedPrefixCache** — the trie re-pointed at the pool: nodes hold
  block IDS, not device segments, so a warm admission is "point the
  row's block table at the matched blocks and incref them" (zero copy,
  zero scatter) and a cold admission's complete prompt blocks join the
  trie by incref alone. Eviction (LRU childless leaves, op-stamp
  protected — the dense trie's exact policy) decrefs back to the pool,
  and the pool calls back into it when allocation starves: ONE shared
  LRU across cached prefixes and free space.
- **`set_block_tables`** — host tables -> every layer's `block_table`
  leaf (the per-row logical-block -> pool-block map the gather uses).

Safety invariants (shared with `_paged_attention`'s docstring):
- the trie holds only COMPLETE prompt blocks, and a warm row's first
  write position (its block-aligned pre_len) opens a fresh private
  block — shared blocks are never written after insertion;
- junk writes (pad feeds of frozen or not-yet-admitted rows) land
  beyond the writer's committed count: in its own allocated cells
  (overwritten position-exactly before any validity mask reaches them)
  or in the null block;
- a freed row's table is re-pointed at the null block BEFORE its next
  program runs, so its frozen one-past-committed pad writes can never
  hit a reallocated block.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.observability import metrics
from tfde_tpu.observability import trace as _trace
from tfde_tpu.inference.prefix_cache import (
    DEFAULT_BYTE_BUDGET,
    is_index_leaf,
)

#: the null block: unallocated table slots point here, out-of-range
#: writes are routed here — never allocated, never read through a mask
NULL_BLOCK = 0


def blocks_for(tokens: int, block: int) -> int:
    """Pool blocks covering `tokens` cells (ceil division)."""
    return -(-int(tokens) // int(block))


def set_block_tables(cache, tables) -> object:
    """Replace every layer's `block_table` leaf with host `tables`
    ([B, nmax] int32). Each leaf gets its OWN device buffer (fresh
    `jnp.asarray` per leaf) — the donated decode scan consumes its cache
    argument, so aliasing one buffer across layers would hand jit the
    same donated buffer twice (the `_set_index_counters` host-mode
    rule)."""
    tables = np.asarray(tables, np.int32)

    def put(path, leaf):
        if str(getattr(path[-1], "key", path[-1])) == "block_table":
            return jnp.asarray(tables)
        return leaf

    return jax.tree_util.tree_map_with_path(put, cache)


def pool_leaf_name(dense_name: str) -> str:
    """Map a dense cache leaf name to its paged twin — the primed
    hand-off ships `cached_key`/`cached_value` segments (layout-agnostic
    [P, heads, dim]); the decode side lands them in `pool_key`/
    `pool_value`."""
    return (dense_name
            .replace("cached_key", "pool_key")
            .replace("cached_value", "pool_value"))


def pool_bytes(cache) -> int:
    """Total pool K/V bytes of a paged batcher cache (index leaves and
    block tables excluded) — the paged ledger's capacity baseline."""
    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        name = str(getattr(path[-1], "key", path[-1]))
        if is_index_leaf(path) or name == "block_table":
            continue
        total += int(leaf.nbytes)
    return total


class PoolExhausted(RuntimeError):
    """Allocation could not be satisfied even after trie eviction —
    admission's capacity gate exists to make this unreachable."""


class BlockPool:
    """Refcounted free-list allocator over the physical KV pool.

    IDs are ints in [1, num_blocks) (0 is the null block). `alloc` takes
    from the free list lowest-id-first (deterministic tests), calling the
    registered evictor — the paged prefix trie — when it starves.
    Listed in tools/tfdelint.py LOCKED_CLASSES: all shared state under
    `_lock`; the evictor is invoked OUTSIDE the lock (it frees blocks
    back through `free`, which takes the lock itself).
    """

    def __init__(self, num_blocks: int, block: int,
                 registry: Optional[metrics.Registry] = None):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the pinned null "
                f"block), got {num_blocks}"
            )
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        self._lock = threading.Lock()
        self._n = int(num_blocks)
        self._block = int(block)
        self._ref = np.zeros(self._n, np.int64)
        self._ref[NULL_BLOCK] = 1          # pinned forever
        self._free: List[int] = list(range(self._n - 1, 0, -1))  # pop -> 1
        self._evictor: Optional[Callable[[int], int]] = None
        self._reg = registry or metrics.default_registry()

    # -- read surface --------------------------------------------------------
    @property
    def num_blocks(self) -> int:
        return self._n

    @property
    def block(self) -> int:
        return self._block

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def refcount(self, bid: int) -> int:
        with self._lock:
            return int(self._ref[bid])

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
        return {
            "total": self._n - 1,         # allocatable (null excluded)
            "free": free,
            "active": self._n - 1 - free,
            "block": self._block,
        }

    # -- allocation ----------------------------------------------------------
    def set_evictor(self, fn: Optional[Callable[[int], int]]) -> None:
        """`fn(need_blocks) -> freed_blocks`, called un-locked when
        `alloc` starves — the paged prefix trie's LRU drain."""
        with self._lock:
            self._evictor = fn

    def available(self, evictable: int = 0) -> int:
        """Blocks an allocation could obtain right now: the free list
        plus what the evictor could reclaim (admission's capacity
        gate)."""
        with self._lock:
            return len(self._free) + int(evictable)

    def alloc(self, n: int) -> List[int]:
        """Take `n` blocks (refcount 1 each). Starvation drains the
        evictor once; still short raises PoolExhausted with everything
        rolled back."""
        if n <= 0:
            return []
        got = self._take(n)
        if len(got) < n and self._evictor is not None:
            self._evictor(n - len(got))
            got += self._take(n - len(got))
        if len(got) < n:
            self.free(got)
            raise PoolExhausted(
                f"need {n} KV blocks, pool has {len(got)} even after "
                f"eviction (size the pool or gate admission)"
            )
        return got

    def incref(self, ids) -> None:
        """Share already-allocated blocks (warm admission / trie
        insert)."""
        with self._lock:
            for b in ids:
                if self._ref[b] < 1:
                    raise ValueError(f"incref of unallocated block {b}")
                self._ref[b] += 1

    def free(self, ids) -> None:
        """Drop one reference per id; a block at refcount 0 returns to
        the free list."""
        with self._lock:
            for b in ids:
                b = int(b)
                if b == NULL_BLOCK:
                    raise ValueError("the null block is pinned")
                if self._ref[b] < 1:
                    raise ValueError(f"double free of block {b}")
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    self._free.append(b)

    def _take(self, n: int) -> List[int]:
        with self._lock:
            self._free.sort(reverse=True)   # deterministic lowest-first
            got = []
            while self._free and len(got) < n:
                b = self._free.pop()
                self._ref[b] = 1
                got.append(b)
            return got

    # -- defrag --------------------------------------------------------------
    def fragmentation(self) -> float:
        """Holes over the occupied span of live ids: 1 - live/max(live)
        (0.0 = perfectly compact or empty). Fixed-size blocks can't
        fragment allocatability, so this measures LOCALITY — how far the
        live set has drifted up the id space — and is the stall-path
        defrag trigger's threshold input (TFDE_KV_DEFRAG_THRESHOLD)."""
        with self._lock:
            live = [b for b in range(1, self._n) if self._ref[b] > 0]
            if not live:
                return 0.0
            return 1.0 - len(live) / float(max(live))

    def defrag(self) -> dict:
        """Compact live blocks to the lowest ids; returns {old: new} for
        every moved block and rewrites the pool's own refcounts/free
        list. Fixed-size blocks can't fragment *allocatability* (any
        free block serves any request), so this exists for locality and
        for the device-side compaction drill — the caller must apply the
        plan to the device pool and every block table (`apply_defrag`)
        BEFORE the next program runs."""
        with self._lock:
            live = sorted(int(b) for b in range(1, self._n)
                          if self._ref[b] > 0)
            plan = {}
            nxt = 1
            for b in live:
                if b != nxt:
                    plan[b] = nxt
                nxt += 1
            if plan:
                ref = np.zeros_like(self._ref)
                ref[NULL_BLOCK] = 1
                for b in live:
                    ref[plan.get(b, b)] = self._ref[b]
                self._ref = ref
                self._free = list(range(self._n - 1, nxt - 1, -1))
            return plan


def apply_defrag(cache, tables: np.ndarray, plan: dict):
    """Apply a `BlockPool.defrag` plan: gather every pool leaf's rows
    into their new ids and rewrite the host tables. Returns (cache,
    tables). One gather per leaf — defrag is a maintenance action, not
    a hot-path one."""
    if not plan:
        return cache, tables
    n = None
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        if str(getattr(path[-1], "key", path[-1])) == "pool_key":
            n = leaf.shape[0]
            break
    perm = np.arange(n, dtype=np.int32)
    for old, new in plan.items():
        perm[new] = old

    def mv(path, leaf):
        name = str(getattr(path[-1], "key", path[-1]))
        # the int8 scale sidecars (TFDE_KV_QUANT) ride the same block ids
        # as their payload, so they permute with it or dequant breaks
        if name in ("pool_key", "pool_value",
                    "pool_key_scale", "pool_value_scale"):
            return leaf[jnp.asarray(perm)]
        return leaf

    cache = jax.tree_util.tree_map_with_path(mv, cache)
    tables = np.asarray(
        [[plan.get(int(b), int(b)) for b in row] for row in tables],
        np.int32,
    )
    return cache, tables


class _Node:
    """One block of one cached prefix path (IDs, not segments)."""

    __slots__ = ("key", "parent", "children", "bid", "last_used", "op")

    def __init__(self, key, parent, bid: int):
        self.key = key
        self.parent = parent
        self.children: dict = {}
        self.bid = bid
        self.last_used = 0
        self.op = 0


class PagedPrefixCache:
    """The prefix trie re-pointed at the block pool.

    Same token-block trie, LRU policy, op-stamp protection, and gauge
    surface as `prefix_cache.PrefixCache`, but a node holds a pool block
    ID the trie has ONE refcount on — lookup hands matched IDs to warm
    admission (which increfs them into the row's table), insert adopts a
    cold row's already-written blocks by incref (zero copy), and
    eviction decrefs back to the pool. Registered as the pool's evictor,
    so allocation pressure drains the trie LRU-first: one LRU shared
    between cached prefixes and free space.

    Single-threaded like the dense trie: only the batcher's step loop
    touches it (the pool's lock covers the cross-thread reads).
    """

    def __init__(self, pool: BlockPool, block_bytes: float,
                 byte_budget: int = DEFAULT_BYTE_BUDGET,
                 registry: Optional[metrics.Registry] = None):
        if byte_budget < 1:
            raise ValueError(f"byte_budget must be >= 1, got {byte_budget}")
        self._pool = pool
        self._block = pool.block
        self._block_bytes = float(block_bytes)
        self._budget = int(byte_budget)
        self._root = _Node(None, None, NULL_BLOCK)
        self._segments = 0
        self._clock = 0
        self._op = 0
        self._hits = 0
        self._misses = 0
        self._reused_tokens = 0
        self._bytes_saved = 0
        self._evictions = 0
        self._reg = registry or metrics.default_registry()

    # -- public -------------------------------------------------------------
    @property
    def block(self) -> int:
        return self._block

    @property
    def byte_budget(self) -> int:
        return self._budget

    @property
    def resident_bytes(self) -> int:
        return int(self._segments * self._block_bytes)

    @property
    def segments(self) -> int:
        return self._segments

    def stats(self) -> dict:
        total = self._hits + self._misses
        return {
            "hits": self._hits,
            "misses": self._misses,
            "hit_rate": self._hits / total if total else 0.0,
            "reused_tokens": self._reused_tokens,
            "bytes": self.resident_bytes,
            "bytes_saved": self._bytes_saved,
            "segments": self._segments,
            "evictions": self._evictions,
        }

    def lookup(self, tokens, trace: Optional[str] = None,
               claim: bool = False):
        """Longest cached prefix usable for prompt `tokens`: ``(L,
        [block ids])`` (L a block multiple, >= 1 suffix token left to
        prefill) or ``(0, None)``. `claim=True` increfs the matched
        blocks for the caller (warm admission's table reference), so no
        eviction between plan and wave can invalidate the IDs — the
        caller owns one `pool.free` per claimed block (`release` undoes
        a partial claim)."""
        tokens = np.asarray(tokens).reshape(-1)
        p = int(tokens.size)
        self._op += 1
        usable = max((p - 1) // self._block, 0)
        node, segs = self._root, []
        while len(segs) < usable:
            b = len(segs)
            key = tuple(
                int(t) for t in tokens[b * self._block:(b + 1) * self._block]
            )
            child = node.children.get(key)
            if child is None:
                break
            segs.append(child)
            node = child
        if not segs:
            self._misses += 1
            self._publish()
            if trace is not None:
                _trace.event("serve/prefix_lookup", trace=trace,
                             hit=False, reused_tokens=0)
            return 0, None
        for s in segs:
            self._clock += 1
            s.last_used = self._clock
            s.op = self._op
        ids = [s.bid for s in segs]
        if claim:
            self._pool.incref(ids)
        n = len(segs)
        self._hits += 1
        self._reused_tokens += n * self._block
        self._bytes_saved += int(n * self._block_bytes)
        self._publish()
        if trace is not None:
            _trace.event("serve/prefix_lookup", trace=trace, hit=True,
                         reused_tokens=n * self._block, prompt_tokens=p)
        return n * self._block, ids

    def release(self, ids) -> None:
        """Undo a claim (a warm plan that shortened or dropped its
        prefix after lookup)."""
        self._pool.free(ids)

    def insert(self, tokens, block_ids) -> int:
        """Adopt the complete blocks of `tokens` whose K/V live in
        `block_ids` (the admitting row's table prefix, already written
        this wave). New nodes incref their block — the trie's own
        reference, independent of the row's. Returns NEW blocks
        adopted; already-resident prefixes are LRU-touched only (the
        row keeps its own private copy of the duplicate block — merging
        would mean rewriting a live table mid-flight). Budget overruns
        evict LRU-first; an unevictable overflow stops the walk."""
        tokens = np.asarray(tokens).reshape(-1)
        nb = min(int(tokens.size) // self._block, len(block_ids))
        if nb == 0:
            return 0
        self._op += 1
        node, created = self._root, 0
        for b in range(nb):
            key = tuple(
                int(t) for t in tokens[b * self._block:(b + 1) * self._block]
            )
            child = node.children.get(key)
            if child is None:
                if ((self._segments + 1) * self._block_bytes > self._budget
                        and not self._evict_blocks(1)):
                    break
                bid = int(block_ids[b])
                self._pool.incref([bid])
                child = _Node(key, node, bid)
                node.children[key] = child
                self._segments += 1
                created += 1
            self._clock += 1
            child.last_used = self._clock
            child.op = self._op
            node = child
        self._publish()
        return created

    def remap(self, plan: dict) -> int:
        """Rewrite node block ids after a `BlockPool.defrag` — the trie's
        references moved with the pool's refcounts, so every node whose
        bid appears in the plan must follow it before the next lookup
        hands stale ids to a warm admission. Returns nodes remapped."""
        if not plan:
            return 0
        moved = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            new = plan.get(node.bid)
            if new is not None:
                node.bid = new
                moved += 1
        return moved

    def evictable_blocks(self) -> int:
        """Childless segments outside the current op — what `evict`
        could reclaim right now (the admission capacity gate's slack
        term)."""
        count = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if not node.children and node.op != self._op:
                count += 1
        return count

    def evict(self, need_blocks: int) -> int:
        """Free >= `need_blocks` trie references LRU-first (childless
        nodes, op-stamp protected); returns blocks freed. The pool's
        registered evictor — a freed block only reaches the free list
        once every sharing row has also released it."""
        return self._evict_blocks(need_blocks)

    # -- internals ----------------------------------------------------------
    def _evict_blocks(self, need: int) -> int:
        freed = 0
        while freed < need:
            victim, stack = None, [self._root]
            while stack:
                nxt = stack.pop()
                for child in nxt.children.values():
                    if child.children:
                        stack.append(child)
                    elif child.op != self._op and (
                            victim is None
                            or child.last_used < victim.last_used):
                        victim = child
            if victim is None:
                break
            del victim.parent.children[victim.key]
            self._pool.free([victim.bid])
            victim.bid = NULL_BLOCK
            freed += 1
            self._segments -= 1
            self._evictions += 1
        if freed:
            self._publish()
        return freed

    def _publish(self) -> None:
        g = self._reg.gauge
        total = self._hits + self._misses
        g("serving/prefix_hits").set(self._hits)
        g("serving/prefix_misses").set(self._misses)
        g("serving/prefix_hit_rate").set(
            self._hits / total if total else 0.0
        )
        g("serving/prefix_reused_tokens").set(self._reused_tokens)
        g("serving/prefix_bytes").set(self.resident_bytes)
        g("serving/prefix_bytes_saved").set(self._bytes_saved)
        g("serving/prefix_segments").set(self._segments)
        g("serving/prefix_evictions").set(self._evictions)
        ref = evictable = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.op == self._op:
                ref += 1
            elif not node.children:
                evictable += 1
        g("kv/trie_blocks").set(self._segments)
        g("kv/trie_bytes").set(self.resident_bytes)
        g("kv/trie_referenced_frac").set(
            ref / self._segments if self._segments else 0.0
        )
        g("kv/trie_evictable_bytes").set(
            int(evictable * self._block_bytes)
        )


def resolve_paged(spec, pool: BlockPool, block_bytes: float
                  ) -> Optional[PagedPrefixCache]:
    """`prefix_cache.resolve` for paged mode: same ``TFDE_PREFIX_CACHE``
    normalization, but the result shares `pool` instead of holding
    device segments. A dense `PrefixCache` instance is refused — its
    segments can't back block tables."""
    if spec is None:
        spec = os.environ.get("TFDE_PREFIX_CACHE", "off").strip().lower()
        if spec in ("", "off", "0", "false", "no"):
            return None
        if spec in ("on", "1", "true", "yes"):
            return PagedPrefixCache(pool, block_bytes)
        try:
            return PagedPrefixCache(pool, block_bytes,
                                    byte_budget=int(spec))
        except ValueError:
            warnings.warn(
                f"TFDE_PREFIX_CACHE={spec!r} is not a recognized value "
                f"(off/on/<int byte budget>); prefix cache stays off",
                stacklevel=2,
            )
            return None
    if isinstance(spec, PagedPrefixCache):
        if spec._pool is not pool:
            raise ValueError(
                "prefix_cache instance was built over a different "
                "BlockPool than this batcher's"
            )
        return spec
    if spec in (False, 0, "off"):
        return None
    if spec in (True, "on"):
        return PagedPrefixCache(pool, block_bytes)
    if isinstance(spec, int):
        return PagedPrefixCache(pool, block_bytes, byte_budget=spec)
    raise ValueError(
        f"unrecognized prefix_cache spec for paged mode: {spec!r} "
        f"(a dense PrefixCache cannot back block tables)"
    )
