"""Inference / serving-side ops: autoregressive generation for the causal
LMs (decode.generate), the capability the reference's SavedModel export
story implies for servable models (SURVEY.md §2a #12)."""

from tfde_tpu.inference.beam import beam_search
from tfde_tpu.inference.decode import (
    generate,
    generate_ragged,
    init_cache,
    sample_logits,
)
from tfde_tpu.inference.speculative import generate_speculative

__all__ = ["ContinuousBatcher", "PrefixCache", "PrimedRequest",
           "ReplicaServer", "Router", "SpeculativeContinuousBatcher",
           "beam_search", "generate",
           "generate_ragged", "generate_speculative", "init_cache",
           "sample_logits"]
from tfde_tpu.inference.prefix_cache import PrefixCache  # noqa: F401
from tfde_tpu.inference.router import (  # noqa: F401
    ReplicaServer,
    Router,
)
from tfde_tpu.inference.server import (  # noqa: F401
    ContinuousBatcher,
    PrimedRequest,
    SpeculativeContinuousBatcher,
)
