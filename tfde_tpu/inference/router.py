"""Multi-replica serving front door: HTTP/SSE routing above the batcher.

One `ContinuousBatcher` is one model replica on one mesh. This module
is the cluster layer that turns N of them into a service:

- `ReplicaServer` wraps one batcher in a stdlib HTTP endpoint: POST
  /generate streams tokens as Server-Sent Events as the batcher's step
  loop produces them (a background thread drives `step()`; request
  handlers only `submit()` and poll `take_progress()`), plus /prime and
  /generate_primed for the prefill/decode role split, /load for the
  router's placement signal, and /healthz. Each replica carries a boot
  ledger (observability/boot.py) whose readiness state (starting ->
  restoring -> compiling -> warming -> ready -> draining) rides
  /healthz and /load; a conventionally constructed replica is ready at
  start(), a cold-booting one passes its externally driven BootLedger
  and the router withholds traffic until it reports ready
  (TFDE_BOOT_READY_* knobs). It optionally pushes its
  serving gauges to the chief (`observability/aggregate.py`
  MetricsPusher), so the whole fleet shows up host-labelled in one
  scrape, and arms the flight recorder for post-mortems.

- `Router` is the front door: POST /v1/generate picks the live replica
  with the fewest outstanding tokens (its own in-flight ledger, plus
  the chief aggregator's host-up/staleness signals when attached) and
  relays the replica's SSE stream. A replica that dies mid-request is
  marked down, recorded + dumped in the flight ring (`replica_down` —
  a SIGKILL'd replica cannot dump its own), and reported to
  `resilience/health.note_replica_down`; requests that had not yet
  streamed a token RE-ROUTE to a survivor transparently, requests
  mid-stream surface a retriable SSE error event. POST /drain marks a
  replica down intentionally (no new placements; in-flight sessions
  finish) — the runbook's graceful-drain knob (WORKFLOWS.md §13).
  When prefill-role replicas are attached, long prompts are primed
  there first and the K/V handed to a decode replica, falling back to
  a plain submit if the prefill tier is down.

Everything is stdlib (http.server / urllib): no new dependencies, and
the wire format is JSON + SSE so `curl` is a debugging tool.
"""

from __future__ import annotations

import base64
import json
import logging
import math
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from tfde_tpu import knobs
from tfde_tpu.inference import admission as _admission
from tfde_tpu.observability import boot as _boot
from tfde_tpu.observability import flightrec, metrics
from tfde_tpu.observability import trace as _trace
from tfde_tpu.observability.slo import SLOTracker

log = logging.getLogger(__name__)

#: connection-level failures that mean "the replica is gone", as opposed
#: to an HTTP error meaning "the request was bad"
_DEAD = (urllib.error.URLError, ConnectionError, socket.timeout,
         TimeoutError, EOFError)


# -- primed-request wire format ----------------------------------------------
def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 et al. (ships with jax)

        return np.dtype(getattr(ml_dtypes, name))


def primed_to_json(primed) -> dict:
    """PrimedRequest -> JSON-safe dict (K/V as base64 raw bytes)."""
    return {
        "prompt": np.asarray(primed.prompt).tolist(),
        "first_token": int(primed.first_token),
        "max_new_tokens": int(primed.max_new_tokens),
        "kv": {
            name: {
                "shape": list(a.shape),
                "dtype": str(a.dtype),
                "data": base64.b64encode(
                    np.ascontiguousarray(a).tobytes()
                ).decode("ascii"),
            }
            for name, a in primed.kv.items()
        },
    }


def primed_from_json(payload: dict):
    from tfde_tpu.inference.server import PrimedRequest

    kv = {
        name: np.frombuffer(
            base64.b64decode(e["data"]), dtype=_np_dtype(e["dtype"])
        ).reshape(e["shape"])
        for name, e in payload["kv"].items()
    }
    return PrimedRequest(
        prompt=np.asarray(payload["prompt"], np.int32),
        first_token=int(payload["first_token"]),
        max_new_tokens=int(payload["max_new_tokens"]),
        kv=kv,
    )


# -- SSE helpers -------------------------------------------------------------
def _sse_write(wfile, obj: dict) -> None:
    wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
    wfile.flush()


def sse_events(fp):
    """Yield parsed `data:` events from a byte stream until EOF."""
    for raw in fp:
        line = raw.strip()
        if line.startswith(b"data: "):
            yield json.loads(line[6:])


def _post_json(url: str, payload: dict, timeout: float, headers=None):
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=hdrs,
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout)


class _FleetHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer tuned for a serving tier: socketserver's
    default listen backlog of 5 silently drops SYNs under a request
    burst — the client's kernel retransmits ~1s later, which shows up
    as a phantom 1s TTFT tail (or a reset) that no server-side metric
    explains. Overload policy belongs to the admission layer (429 +
    Retry-After), so accept the burst and let it decide."""

    daemon_threads = True
    request_queue_size = 128


# -- replica-side server -----------------------------------------------------
class ReplicaServer:
    """One batcher replica behind HTTP/SSE (see the module docstring).

    The batcher is driven by an internal step-loop thread; HTTP handlers
    hold `lock` only to submit and to drain `take_progress`, so a long
    decode scan never blocks accepting work for the next one.
    `replica_id` doubles as the metrics `host` label when `push_url`
    (the chief/router's /push endpoint) is given — keep it equal to the
    replica's index in the router's replica list.
    """

    def __init__(self, batcher, port: int = 0, host: str = "127.0.0.1",
                 replica_id: int = 0, push_url: Optional[str] = None,
                 push_interval: float = 2.0,
                 model_dir: Optional[str] = None,
                 poll_interval: float = 0.002,
                 boot_ledger=None):
        self.batcher = batcher
        batcher.enable_progress()
        self.replica_id = int(replica_id)
        self.lock = threading.RLock()
        self._poll = float(poll_interval)
        self._stop = threading.Event()
        # readiness: an externally driven BootLedger (a cold-booting
        # replica advances its phases and calls ready() itself); without
        # one the replica is ready the moment start() returns — the
        # conventional in-process construction has no boot to measure
        self._boot_external = boot_ledger is not None
        self.boot = (boot_ledger if boot_ledger is not None
                     else _boot.BootLedger())
        if model_dir is not None:
            flightrec.arm(model_dir)
            _trace.arm(model_dir)
        # usage metering JSONL (TFDE_USAGE_LOG=on) anchors to the same
        # model_dir as the flight ring and trace dumps
        batcher.arm_usage_log(model_dir)
        # label this process's trace events (a lone replica per process
        # in the cluster deployment — the stitched waterfall's row name)
        _trace.set_process(f"replica{self.replica_id}")
        # serving-side bounded capture: a RoundWindowProfiler over decode
        # rounds, armable by POST /profile or any hub trigger (SLO burn,
        # recompile storm, coordinated broadcast)
        from tfde_tpu.observability import profiler as profiler_lib

        self.profiler = profiler_lib.RoundWindowProfiler(
            model_dir,
            artifacts=(profiler_lib.ProfileArtifacts(model_dir)
                       if model_dir is not None else None),
        )
        batcher.attach_profiler(self.profiler)
        self._hub_sink = f"replica{self.replica_id}_round_window"
        profiler_lib.hub().register(self._hub_sink, self.profiler.trigger_sink)
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"  # close-delimited SSE streams

            def log_message(self, *a):  # quiet; metrics carry the signal
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    # liveness stays a 200 (the process answers); the
                    # READINESS state rides the body so pollers and the
                    # router can tell "up" from "safe to place on"
                    state = srv.state
                    srv._send_json(self, 200, {
                        "ok": state == "ready",
                        "state": state,
                        "replica": srv.replica_id,
                    })
                elif self.path == "/load":
                    srv._send_json(self, 200, srv.load())
                elif self.path.startswith("/trace/"):
                    # this process's ring slice for one trace id — the
                    # chief collector stitches these across replicas
                    tid = self.path[len("/trace/"):]
                    srv._send_json(self, 200, {
                        "proc": _trace.process(), "trace": tid,
                        "events": _trace.events(tid),
                    })
                else:
                    self.send_error(404)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    srv._send_json(self, 400, {"error": "bad json"})
                    return
                try:
                    if self.path == "/generate":
                        srv._handle_generate(self, body, primed=False)
                    elif self.path == "/generate_primed":
                        srv._handle_generate(self, body, primed=True)
                    elif self.path == "/prime":
                        srv._handle_prime(self, body)
                    elif self.path == "/profile":
                        srv._handle_profile(self, body)
                    else:
                        self.send_error(404)
                except _admission.QueueFull as e:
                    # typed overload rejection — MUST precede the
                    # RuntimeError clause below or it degrades to a 400
                    # that tells the client to fix a request that was
                    # fine. Retry-After is the drain-rate estimate,
                    # integer-seconds per the HTTP spec (the precise
                    # float rides the JSON body).
                    metrics.default_registry().counter(
                        "serving/rejected_429").incr()
                    flightrec.record("admission_reject",
                                     replica=srv.replica_id,
                                     reason=e.reason,
                                     queue_depth=e.queue_depth,
                                     retry_after_s=e.retry_after_s)
                    srv._send_json(
                        self, 429, e.as_json(),
                        headers={"Retry-After":
                                 str(max(1, math.ceil(e.retry_after_s)))},
                    )
                except (ValueError, RuntimeError) as e:
                    srv._send_json(self, 400, {"error": str(e)})

        self._httpd = _FleetHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"tfde-replica-{replica_id}-http",
        )
        self._loop_thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"tfde-replica-{replica_id}-step",
        )
        self._pusher = None
        if push_url is not None:
            from tfde_tpu.observability.aggregate import MetricsPusher

            self._pusher = MetricsPusher(
                push_url, interval=push_interval, host=self.replica_id,
            )

    def start(self) -> "ReplicaServer":
        self._http_thread.start()
        self._loop_thread.start()
        if not self._boot_external:
            # no external boot driver: the batcher was built (and warmed)
            # before construction, so the replica is ready now
            self.boot.ready()
        log.info("replica %d serving on %s (state %s)",
                 self.replica_id, self.url, self.state)
        return self

    @property
    def state(self) -> str:
        """Readiness state surfaced on /healthz and /load: the boot
        ledger's machine until ready, `draining` once close() begins."""
        return self.boot.state

    def close(self) -> None:
        self.boot.draining()
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._pusher is not None:
            self._pusher.close()
        from tfde_tpu.observability import profiler as profiler_lib

        profiler_lib.hub().unregister(self._hub_sink)
        self.profiler.close()
        _trace.dump("replica_close")

    def _handle_profile(self, handler, body: dict) -> None:
        """POST /profile {"span": N?, "reason": str?} — arm a bounded
        decode-round capture on this replica. 409 when one is already
        armed/active or the replica has no local model_dir to trace to."""
        span = body.get("span")
        reason = str(body.get("reason") or "operator")
        armed = self.profiler.arm(
            span=int(span) if span is not None else None, reason=reason,
        )
        self._send_json(handler, 200 if armed else 409, {
            "replica": self.replica_id, "armed": armed, "reason": reason,
        })

    def load(self) -> dict:
        # the batcher's contract is "single-threaded under the external
        # ReplicaServer.lock"; reading its queue while the step loop
        # mutates it is the exact race tfdelint's guarded_attrs audit
        # exists to flag
        with self.lock:
            b = self.batcher
            depth = len(b._queue)
            queued_tokens = b.queued_tokens
            kv = b.kv_stats()
            reason = b.admission.would_reject(
                depth, queued_tokens,
                headroom_rows=kv.get("headroom_rows"))
            # Retry-After basis: the queued backlog — unless the MEMORY
            # gate is what binds, where headroom frees as ACTIVE rows
            # finish, so the outstanding decode backlog is the honest
            # drain estimate (the queue may well be empty)
            backlog = queued_tokens
            if reason == "kv_headroom":
                backlog = max(backlog, b.outstanding_tokens)
            return {
                "replica": self.replica_id,
                "role": b.role,
                "state": self.state,
                "boot": self.boot.snapshot(),
                "outstanding_tokens": b.outstanding_tokens,
                "queue_depth": depth,
                "queue_depths": b._queue.depths(),
                "queued_tokens": queued_tokens,
                "free_rows": b.free_rows,
                "drain_rate_tps": b.admission.drain_rate_tps,
                "retry_after_s": b.admission.retry_after_s(backlog),
                "saturated": reason is not None,
                "kv": kv,
            }

    # -- internals ----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            with self.lock:
                idle = self.batcher.idle
                if not idle:
                    self.batcher.step()
            if idle:
                time.sleep(self._poll)

    @staticmethod
    def _send_json(handler, code: int, obj: dict, headers=None) -> None:
        body = json.dumps(obj).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)

    def _handle_prime(self, handler, body: dict) -> None:
        tid = handler.headers.get(_trace.HEADER)
        with self.lock:
            primed = self.batcher.prime(
                body["prompt"], int(body["max_new_tokens"]), trace=tid
            )
        self._send_json(handler, 200, primed_to_json(primed))

    def _handle_generate(self, handler, body: dict, primed: bool) -> None:
        tid = handler.headers.get(_trace.HEADER)
        # the header wins over the body field: a primed hand-off's body
        # is the K/V payload, so the class can only ride the header there
        pr = _admission.validate_priority(
            handler.headers.get(_admission.PRIORITY_HEADER)
            or body.get("priority"))
        dl = body.get("ttft_deadline_ms")
        dl = float(dl) if dl is not None else None
        t_req = time.perf_counter()
        with self.lock:
            if primed:
                rid = self.batcher.submit_primed(
                    primed_from_json(body), trace=tid,
                    priority=pr, ttft_deadline_ms=dl)
            else:
                rid = self.batcher.submit(
                    body["prompt"], int(body["max_new_tokens"]), trace=tid,
                    priority=pr, ttft_deadline_ms=dl,
                )
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            if tid:
                handler.send_header(_trace.HEADER, tid)
            handler.end_headers()
            first = {"rid": rid, "replica": self.replica_id}
            if tid:
                first["trace"] = tid
            _sse_write(handler.wfile, first)
            sent = 0
            while True:
                with self.lock:
                    toks, done = self.batcher.take_progress(rid)
                    shed = done and self.batcher.was_shed(rid)
                for t in toks:
                    _sse_write(handler.wfile, {"token": int(t)})
                    sent += 1
                if shed:
                    # deadline-shed at dequeue: the SSE headers already
                    # went out when we accepted the submit, so the 429
                    # moment has passed — report the shed in-band as a
                    # retriable error instead of a silent empty `done`
                    with self.lock:
                        ra = self.batcher.admission.retry_after_s(
                            self.batcher.queued_tokens)
                    _sse_write(handler.wfile,
                               {"error": "deadline_shed", "shed": True,
                                "retriable": True,
                                "retry_after_s": round(ra, 3)})
                    return
                if done:
                    _sse_write(handler.wfile, {"done": True, "n": sent})
                    if tid is not None and _trace.active():
                        # the replica-side bracket: submit -> last SSE
                        # byte flushed (decode AND relay)
                        _trace.event("serve/stream_out", trace=tid,
                                     rid=rid, tokens=sent,
                                     dur=time.perf_counter() - t_req)
                    return
                time.sleep(self._poll)
        except (BrokenPipeError, ConnectionResetError):
            # the consumer is gone (router timeout / client disconnect):
            # without the cancel the request would decode to completion
            # on abandoned work and its progress entry would leak forever
            with self.lock:
                self.batcher.cancel(rid)


# -- router ------------------------------------------------------------------
class _Replica:
    __slots__ = ("url", "idx", "up", "outstanding", "served", "drained",
                 "state", "ready_seen", "first_seen")

    def __init__(self, url: str, idx: int):
        self.url = url.rstrip("/")
        self.idx = idx
        self.up = True
        self.drained = False
        self.outstanding = 0   # router-side in-flight token estimate
        self.served = 0
        # readiness (observability/boot.py): last /load-reported state
        # ("unknown" until the first snapshot — fail open), whether this
        # replica has EVER reported ready (distinguishes a lost replica
        # from one that never finished booting), and when the router
        # first saw it (the boot-grace anchor)
        self.state = "unknown"
        self.ready_seen = False
        self.first_seen = time.monotonic()


class Router:
    """Least-outstanding-tokens front door over replica endpoints (see
    the module docstring).

    replicas: decode-capable replica base URLs; index order must match
    each `ReplicaServer.replica_id` so the chief aggregator's
    host-labelled gauges line up with the routing table.
    prefill_replicas: optional prefill-role tier for the role split;
    prompts of at least `prefill_min_tokens` are primed there first.
    aggregator: a `ClusterAggregator` receiving replica pushes — adds
    push-staleness (host-up flip) as a down signal on top of the
    router's own connection-failure detection.
    slo: an `SLOTracker` (one is built from the TFDE_SLO_* environment
    when omitted) fed the CLIENT-observed TTFT/TPOT of every routed
    session — queueing, placement, re-routes and the primed hand-off
    included; its gauges ride /metrics and its summary the /replicas
    table.

    Every /v1/generate session gets a trace id (X-Tfde-Trace — the
    incoming header is honored so callers can bring their own),
    propagated to the replicas and returned to the client in the
    response header, the SSE `meta` event, and the final payload. The
    id is cheap to mint; actual event RECORDING stays off unless the
    trace ring is enabled (TFDE_TRACE). GET /trace/<id> answers the
    stitched cross-process waterfall.
    """

    def __init__(self, replicas, prefill_replicas=(), port: int = 0,
                 host: str = "127.0.0.1", aggregator=None,
                 model_dir: Optional[str] = None,
                 prefill_min_tokens: int = 0,
                 request_timeout: float = 120.0,
                 slo: Optional[SLOTracker] = None,
                 brownout_burn: Optional[float] = None,
                 brownout_burn_batch: Optional[float] = None):
        if not replicas:
            raise ValueError("need at least one replica URL")
        self._reps = [_Replica(u, i) for i, u in enumerate(replicas)]
        self._pre = [_Replica(u, i) for i, u in enumerate(prefill_replicas)]
        self._agg = aggregator
        self._pmin = int(prefill_min_tokens)
        self._timeout = float(request_timeout)
        self._lock = threading.Lock()
        self._reg = metrics.default_registry()
        self._slo = slo if slo is not None else SLOTracker()
        # brownout: fast-window TTFT burn past `brownout_burn` sheds
        # best_effort; past `brownout_burn_batch` sheds batch too.
        # interactive is never brownout-shed — past that point the
        # admission caps are the backstop.
        self._brownout_burn = float(
            brownout_burn if brownout_burn is not None
            else knobs.env_float("TFDE_BROWNOUT_BURN", 8.0))
        self._brownout_burn_batch = float(
            brownout_burn_batch if brownout_burn_batch is not None
            else knobs.env_float("TFDE_BROWNOUT_BURN_BATCH", 16.0))
        self._brownout_level = 0   # 0 off, 1 shed best_effort, 2 + batch
        # /load snapshot cache: saturation is polled per request but the
        # GETs go out at most once per TTL — overload is exactly when a
        # per-request fan-out would make things worse
        self._loads: dict = {}
        self._loads_at = 0.0
        self._load_ttl = 0.25
        # trace id -> replica idx currently relaying it; read by
        # _mark_down so a replica_down flight breadcrumb names the
        # in-flight traces it stranded
        self._inflight: dict = {}
        if model_dir is not None:
            flightrec.arm(model_dir)
            _trace.arm(model_dir)
        _trace.set_process("router")
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    body = b"ok\n"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/replicas":
                    ReplicaServer._send_json(
                        self, 200,
                        {"replicas": router.table(),
                         "slo": router.slo.summary(),
                         "mem": router.mem_table(),
                         "kv": router.kv_table(),
                         "boot": router.boot_table()},
                    )
                elif self.path.startswith("/trace/"):
                    tid = self.path[len("/trace/"):]
                    ReplicaServer._send_json(self, 200,
                                             router.trace(tid))
                else:
                    self.send_error(404)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    ReplicaServer._send_json(self, 400,
                                             {"error": "bad json"})
                    return
                if self.path == "/v1/generate":
                    router._serve_generate(self, body)
                elif self.path == "/drain":
                    try:
                        idx = int(body["replica"])
                        tier = str(body.get("tier", "decode"))
                        if tier not in ("decode", "prefill"):
                            raise ValueError(f"unknown tier {tier!r}")
                    except (KeyError, TypeError, ValueError) as e:
                        ReplicaServer._send_json(
                            self, 400,
                            {"error": f"need integer 'replica' "
                                      f"(+ optional tier): {e}"},
                        )
                        return
                    if router.drain(idx, tier):
                        ReplicaServer._send_json(
                            self, 200, {"drained": idx, "tier": tier}
                        )
                    else:
                        ReplicaServer._send_json(
                            self, 404,
                            {"error": f"unknown {tier} replica {idx}"},
                        )
                elif self.path == "/profile":
                    ReplicaServer._send_json(
                        self, 200, router.profile_all(body))
                else:
                    self.send_error(404)

        self._httpd = _FleetHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tfde-router-http",
        )

    def start(self) -> "Router":
        self._http_thread.start()
        log.info("router serving on %s over %d replica(s)",
                 self.url, len(self._reps))
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        _trace.dump("router_close")

    @property
    def slo(self) -> SLOTracker:
        return self._slo

    def profile_all(self, body: dict) -> dict:
        """POST /profile fan-out: forward the arm request to every decode
        and prefill replica; per-replica armed/refused results (a down
        replica reports armed=False with its error). Fleet-wide capture
        from one operator call — the serving face of the coordinated
        cross-host window."""
        payload = {"reason": str(body.get("reason") or "operator")}
        if body.get("span") is not None:
            payload["span"] = int(body["span"])
        results = []
        for rep in self._reps + self._pre:
            try:
                with _post_json(f"{rep.url}/profile", payload,
                                timeout=5.0) as resp:
                    results.append(json.loads(resp.read()))
            except urllib.error.HTTPError as e:
                try:
                    results.append(json.loads(e.read()))
                except Exception:
                    results.append({"replica": rep.idx, "armed": False,
                                    "error": str(e)})
            except Exception as e:  # noqa: BLE001 — dead replica
                results.append({"replica": rep.idx, "armed": False,
                                "error": str(e)})
        return {"reason": payload["reason"], "replicas": results}

    def trace(self, trace_id: str) -> dict:
        """Stitch one request's waterfall across this router and every
        replica (live ones answer /trace/<id>; dead ones contribute
        nothing) — the chief-side collector entry point."""
        from tfde_tpu.observability.aggregate import collect_trace

        urls = [r.url for r in self._reps] + [r.url for r in self._pre]
        return collect_trace(trace_id, urls,
                             local_events=_trace.events(trace_id))

    # -- placement ----------------------------------------------------------
    def _refresh_liveness(self) -> None:
        """Fold the chief aggregator's staleness view into the routing
        table: a replica whose metric pushes went stale is down even if
        the router has not yet hit a connection error on it. A replica
        that has never been ready gets TFDE_BOOT_READY_GRACE_S first —
        a joiner mid-compile-storm pushes late because it is busy
        booting, not because it died."""
        if self._agg is None:
            return
        grace = _boot.ready_grace_s()
        hosts = self._agg.hosts()
        now = time.monotonic()
        for rep in self._reps:
            info = hosts.get(rep.idx)
            if info is None or info["age"] <= self._agg.stale_after:
                continue
            if not rep.ready_seen and now - rep.first_seen < grace:
                continue
            self._mark_down(rep, f"stale push ({info['age']:.1f}s)")

    def _placeable(self, rep: _Replica) -> bool:
        """Readiness gate (decode tier): place only on replicas whose
        last /load snapshot said `ready` — or that the router has never
        snapshotted (fail open, the pre-readiness behavior for legacy
        replicas and direct-`_pick` callers)."""
        return rep.state in _boot.PLACEABLE_STATES

    def _pick(self, pool, exclude=()):
        self._refresh_liveness()
        gate = pool is self._reps and _boot.ready_require()
        with self._lock:
            cands = [r for r in pool
                     if r.up and not r.drained and r.idx not in exclude
                     and (not gate or self._placeable(r))]
            if not cands:
                raise LookupError("no live replicas")
            return min(cands, key=lambda r: r.outstanding)

    def _account(self, rep: _Replica, outstanding: int = 0,
                 served: int = 0) -> None:
        """Handler threads run concurrently while `_pick` reads the
        counters under the lock — every read-modify-write must be atomic
        or a lost update skews least-outstanding placement for the rest
        of the process lifetime."""
        with self._lock:
            rep.outstanding += outstanding
            rep.served += served

    def _mark_down(self, rep: _Replica, reason: str) -> None:
        with self._lock:
            if not rep.up:
                return
            rep.up = False
            # fail open like placement does: a replica the router never
            # snapshotted (state "unknown") gets legacy `lost`
            # accounting; only an OBSERVED not-yet-ready boot books as
            # never_ready
            ever_ready = rep.ready_seen or rep.state == "unknown"
            # the traces this death strands — the flight dump's
            # cross-reference into the request-trace timeline
            stranded = sorted(
                t for t, idx in self._inflight.items() if idx == rep.idx
            )
        log.warning("replica %d (%s) down: %s%s", rep.idx, rep.url, reason,
                    "" if ever_ready else " (never became ready)")
        # a replica that died WITHOUT ever reaching ready is a failed
        # boot, not lost serving capacity — the autoscaler reads these
        # two counters very differently
        self._reg.counter("router/replicas_lost" if ever_ready
                          else "router/replicas_never_ready").incr()
        self._reg.gauge(f"router/replica{rep.idx}/up").set(0)
        from tfde_tpu.resilience.health import note_replica_down

        note_replica_down(rep.idx, reason)
        # the dead replica can't dump its own flight ring (SIGKILL);
        # the router's ring carries the routing-side story for it
        flightrec.record("replica_down", replica=rep.idx, reason=reason,
                         never_ready=not ever_ready, traces=stranded)
        flightrec.dump("replica_down")

    def drain(self, idx: int, tier: str = "decode") -> bool:
        """Stop placing new sessions on replica `idx` of `tier`
        ('decode' or 'prefill'); in-flight streams finish on their own.
        The graceful half of replica removal. Returns whether the index
        named a known replica."""
        if tier not in ("decode", "prefill"):
            raise ValueError(f"unknown drain tier {tier!r}")
        pool = self._pre if tier == "prefill" else self._reps
        label = "prefill" if tier == "prefill" else "replica"
        for rep in pool:
            if rep.idx == idx:
                with self._lock:
                    rep.drained = True
                self._reg.gauge(f"router/{label}{idx}/drained").set(1)
                flightrec.record("replica_drain", replica=idx, tier=tier)
                return True
        return False

    def mem_table(self) -> dict:
        """Per-replica memory & compile snapshot from the pushed metrics
        (the mem/* block on obs_dump --router): live device bytes, the
        largest registered program's peak, and the sentinel's total
        cache-miss count — enough to spot an HBM leak or a recompiling
        replica from the routing table without scraping each replica."""
        if self._agg is None:
            return {}
        out = {}
        for hid, flat in self._agg.host_metrics(("mem/", "compile/")).items():
            peaks = {name[len("mem/"):-len("/peak_bytes")]: v
                     for name, v in flat.items()
                     if name.startswith("mem/")
                     and name.endswith("/peak_bytes")}
            top = max(peaks.items(), key=lambda kv: kv[1], default=None)
            out[str(hid)] = {
                "live_bytes": flat.get("mem/live/bytes"),
                "live_buffers": flat.get("mem/live/buffers"),
                "peak_program": top[0] if top else None,
                "peak_bytes": top[1] if top else None,
                "compile_misses": sum(
                    v for name, v in flat.items()
                    if name.startswith("compile/")
                    and name.endswith("/misses")),
                "compile_seconds": flat.get("compile/seconds_total"),
            }
        return out

    def kv_table(self) -> dict:
        """Per-replica KV occupancy/headroom snapshot from the pushed
        metrics (the kv block on /replicas and obs_dump --capacity):
        how full each replica's dense slab is, what pad-ladder waste it
        carries, and how many more rows fit — the fleet's capacity
        picture without scraping each replica."""
        if self._agg is None:
            return {}
        out = {}
        for hid, flat in self._agg.host_metrics(("kv/",)).items():
            if "kv/allocated_bytes" not in flat:
                continue
            # worst pad-ladder cell: the bucket whose cumulative pad
            # waste is largest — the cells paged-KV would reclaim first
            pre = "kv/pad_waste_tokens/bucket_"
            buckets = {int(name[len(pre):]): v for name, v in flat.items()
                       if name.startswith(pre)}
            top = max(buckets.items(), key=lambda kv: kv[1], default=None)
            out[str(hid)] = {
                "allocated_bytes": flat.get("kv/allocated_bytes"),
                "used_bytes": flat.get("kv/used_bytes"),
                "waste_frac": flat.get("kv/waste_frac"),
                "rows_active": flat.get("kv/rows_active"),
                "rows_free": flat.get("kv/rows_free"),
                "headroom_rows": flat.get("kv/headroom_rows"),
                "headroom_tokens": flat.get("kv/headroom_tokens"),
                "trie_bytes": flat.get("kv/trie_bytes"),
                "pad_waste_tokens": flat.get("kv/pad_waste_tokens"),
                "top_waste_bucket": top[0] if top else None,
                "top_waste_bucket_tokens": top[1] if top else None,
            }
        return out

    def table(self) -> list:
        """Live routing table (the obs_dump --router surface)."""
        ages = self._agg.hosts() if self._agg is not None else {}
        rows = []
        for rep in self._reps:
            info = ages.get(rep.idx, {})
            rows.append({
                "replica": rep.idx,
                "url": rep.url,
                "up": rep.up,
                "drained": rep.drained,
                "state": "draining" if rep.drained else rep.state,
                "ready_seen": rep.ready_seen,
                "outstanding_tokens": rep.outstanding,
                "served": rep.served,
                "push_age_s": info.get("age"),
            })
        return rows

    def boot_table(self) -> dict:
        """Per-replica boot ledger (the /replicas `boot` block and
        obs_dump --boot surface): the cached /load snapshot's full
        ledger when the router has one, back-filled from the pushed
        boot/* gauges for replicas it has not snapshotted (e.g. a chief
        aggregating hosts the router never placed on)."""
        with self._lock:
            loads = dict(self._loads)
        out = {}
        for idx, ld in loads.items():
            if isinstance(ld, dict) and isinstance(ld.get("boot"), dict):
                out[str(idx)] = ld["boot"]
        if self._agg is not None:
            for hid, flat in self._agg.host_metrics(("boot/",)).items():
                if not flat or str(hid) in out:
                    continue
                phases = {
                    name: flat[g] for name, g in (
                        ("init", "boot/init_seconds"),
                        ("bootstrap", "boot/bootstrap_seconds"),
                        ("restore", "boot/restore_seconds"),
                        ("compile", "boot/compile_wall_seconds"),
                        ("warmup", "boot/warmup_seconds"),
                    ) if g in flat
                }
                out[str(hid)] = {
                    "state": None,   # gauges carry numbers, not the FSM
                    "phases": phases,
                    "time_to_ready_s": flat.get(
                        "boot/time_to_ready_seconds"),
                    "ttft_from_birth_ms": flat.get(
                        "boot/ttft_from_birth_ms"),
                    "restore": {"bandwidth_bps": flat.get(
                        "boot/restore_bandwidth_bps")},
                    "compile": {
                        "boot_count": flat.get("boot/compile_count"),
                        "boot_seconds": flat.get("boot/compile_seconds"),
                    },
                }
        return out

    def _publish(self) -> None:
        for rep in self._reps:
            g = self._reg.gauge
            g(f"router/replica{rep.idx}/up").set(int(rep.up))
            g(f"router/replica{rep.idx}/outstanding_tokens").set(
                rep.outstanding
            )
            g(f"router/replica{rep.idx}/served").set(rep.served)

    # -- overload protection -------------------------------------------------
    def _brownout_shed_rank(self) -> int:
        """The minimum PRIORITY_RANK this router currently sheds: 3 when
        brownout is off (no class has rank 3 — nothing sheds), 2 at
        level 1 (best_effort), 1 at level 2 (batch too). interactive
        (rank 0) is never brownout-shed. Level changes are edge-detected
        into a gauge + flight breadcrumb, the ProfileTrigger idiom."""
        level = 0
        count, att = self._slo.window_stats("ttft", self._slo.windows[0])
        if count >= 8 and att is not None:  # slo.MIN_BURN_SAMPLES
            burn = (1.0 - att) / (1.0 - self._slo.objective)
            if self._brownout_burn > 0 and burn >= self._brownout_burn:
                level = 1
            if (self._brownout_burn_batch > 0
                    and burn >= self._brownout_burn_batch):
                level = 2
        with self._lock:
            changed = level != self._brownout_level
            self._brownout_level = level
        if changed:
            self._reg.gauge("router/brownout_level").set(level)
            flightrec.record("brownout", level=level,
                             burn_threshold=self._brownout_burn)
            log.warning("brownout level -> %d", level)
        return 3 - level

    def _load_snapshot(self) -> dict:
        """replica idx -> its /load JSON, for live decode replicas,
        refreshed at most once per `_load_ttl`. A replica that fails the
        GET is simply absent (liveness is _pick's job, not this path's)."""
        now = time.monotonic()
        with self._lock:
            if now - self._loads_at < self._load_ttl:
                return self._loads
        loads = {}
        for rep in self._reps:
            if not rep.up or rep.drained:
                continue
            try:
                with urllib.request.urlopen(
                        rep.url + "/load", timeout=2.0) as resp:
                    loads[rep.idx] = json.loads(resp.read())
            except Exception:  # noqa: BLE001 — absent, not dead
                continue
        with self._lock:
            self._loads = loads
            self._loads_at = now
            # readiness refresh rides the same snapshot: every request
            # path calls this before _pick, so placement always gates on
            # a state at most _load_ttl old. A /load without `state` is
            # a legacy replica — treat as ready.
            for rep in self._reps:
                ld = loads.get(rep.idx)
                if ld is None:
                    continue
                rep.state = str(ld.get("state", "ready"))
                if rep.state == "ready":
                    rep.ready_seen = True
        return loads

    def _reject(self, handler, headers_sent: bool, reason: str,
                retry_after_s: float, tid: Optional[str]) -> None:
        """One well-formed 429 (or in-band SSE error when the stream is
        already open): counted per reason, breadcrumbed, Retry-After in
        integer seconds with the precise float in the body."""
        self._reg.counter("router/rejected_429").incr()
        self._reg.counter(f"router/rejected_{reason}").incr()
        flightrec.record("router_reject", reason=reason,
                         retry_after_s=round(retry_after_s, 3))
        body = {"error": "overloaded", "reason": reason,
                "retriable": True,
                "retry_after_s": round(retry_after_s, 3)}
        if headers_sent:
            _sse_write(handler.wfile, body)
        else:
            headers = {"Retry-After": str(max(1, math.ceil(retry_after_s)))}
            if tid:
                headers[_trace.HEADER] = tid
            ReplicaServer._send_json(handler, 429, body, headers=headers)

    # -- request path --------------------------------------------------------
    def _maybe_prime(self, body: dict, tid: Optional[str] = None):
        """Run the prefill on the prefill tier when configured; returns
        the primed JSON payload or None (fall back to a plain submit)."""
        if not self._pre or len(body["prompt"]) < self._pmin:
            return None
        exclude: list = []
        while True:
            try:
                rep = self._pick(self._pre, exclude)
            except LookupError:
                return None  # prefill tier down: decode replicas prefill
            try:
                self._account(rep, outstanding=len(body["prompt"]))
                try:
                    t0 = time.perf_counter()
                    with _post_json(
                        rep.url + "/prime",
                        {"prompt": body["prompt"],
                         "max_new_tokens": body["max_new_tokens"]},
                        self._timeout,
                        headers={_trace.HEADER: tid} if tid else None,
                    ) as resp:
                        out = json.loads(resp.read())
                    if _trace.active() and tid is not None:
                        # the router-observed prime round trip: the
                        # prefill replica's own serve/prime nests inside
                        _trace.event("router/prime", trace=tid,
                                     prefill_replica=rep.idx,
                                     dur=time.perf_counter() - t0)
                finally:
                    self._account(rep, outstanding=-len(body["prompt"]))
                self._account(rep, served=1)
                return out
            except urllib.error.HTTPError:
                return None   # request-specific: let the decode tier try
            except _DEAD as e:
                self._mark_down(rep, f"prime: {e}")
                exclude.append(rep.idx)

    def _serve_generate(self, handler, body: dict) -> None:
        """Route one session; re-route on replica death until first
        token, retriable SSE error after."""
        try:
            budget = int(body["max_new_tokens"])
            prompt = list(body["prompt"])
        except (KeyError, TypeError, ValueError):
            ReplicaServer._send_json(
                handler, 400, {"error": "need prompt + max_new_tokens"}
            )
            return
        try:
            priority = _admission.validate_priority(
                handler.headers.get(_admission.PRIORITY_HEADER)
                or body.get("priority"))
        except ValueError as e:
            ReplicaServer._send_json(handler, 400, {"error": str(e)})
            return
        ttft_deadline_ms = body.get("ttft_deadline_ms")
        stream = bool(body.get("stream", False))
        # every session has a trace id (honor the caller's, else mint):
        # propagation + echo-back are unconditional and cheap; span
        # RECORDING stays behind the TFDE_TRACE ring flag
        tid = handler.headers.get(_trace.HEADER) or _trace.new_id()
        t_req = time.perf_counter()
        self._reg.counter("router/requests").incr()
        if _trace.active():
            _trace.event("router/request", trace=tid,
                         prompt_tokens=len(prompt), budget=budget,
                         priority=priority)
        # brownout gate: under sustained SLO burn, the lowest classes
        # are turned away at the front door before any replica spends a
        # prefill on them
        if (_admission.PRIORITY_RANK[priority]
                >= self._brownout_shed_rank()):
            self._reject(handler, False, "brownout",
                         _admission.MIN_RETRY_AFTER_S * 4, tid)
            return
        # saturation gate: when EVERY live PLACEABLE replica's /load
        # snapshot says its admission controller would reject, fail fast
        # here with the fleet's best Retry-After instead of bouncing off
        # each replica (a warming joiner is not capacity yet, so it
        # neither saves nor dooms the fleet here)
        all_loads = self._load_snapshot()
        gated = _boot.ready_require()
        loads = {idx: ld for idx, ld in all_loads.items()
                 if not gated
                 or str(ld.get("state", "ready")) in _boot.PLACEABLE_STATES}
        sat = [ld for ld in loads.values() if ld.get("saturated")]
        if loads and len(sat) == len(loads):
            self._reject(handler, False, "saturated",
                         min(ld.get("retry_after_s", 1.0) for ld in sat),
                         tid)
            return
        primed_payload = self._maybe_prime(body, tid)
        headers_sent = False
        exclude: list = []
        sat429: list = []   # Retry-After estimates from per-replica 429s
        while True:
            try:
                rep = self._pick(self._reps, exclude)
            except LookupError:
                if sat429:
                    # every live replica answered 429: the cluster is
                    # saturated, not down — tell the client to back off,
                    # with the most optimistic replica's estimate
                    self._reject(handler, headers_sent, "saturated",
                                 min(sat429), tid)
                    return
                if headers_sent:
                    _sse_write(handler.wfile,
                               {"error": "no live replicas",
                                "retriable": True})
                else:
                    ReplicaServer._send_json(
                        handler, 503, {"error": "no live replicas"},
                        headers={_trace.HEADER: tid},
                    )
                return
            if exclude:
                self._reg.counter("router/reroutes").incr()
            if _trace.active():
                # one event per placement attempt: a re-routed request's
                # waterfall shows the dead replica AND the survivor
                _trace.event("router/attempt", trace=tid, replica=rep.idx,
                             rerouted=bool(exclude),
                             primed=primed_payload is not None)
            self._account(rep, outstanding=budget)
            with self._lock:
                self._inflight[tid] = rep.idx
            tokens: list = []
            relayed = 0
            t_first = None
            finished = False
            try:
                fwd_headers = {_trace.HEADER: tid,
                               _admission.PRIORITY_HEADER: priority}
                if primed_payload is not None:
                    req = _post_json(rep.url + "/generate_primed",
                                     primed_payload, self._timeout,
                                     headers=fwd_headers)
                else:
                    fwd_body = {"prompt": prompt,
                                "max_new_tokens": budget,
                                "priority": priority}
                    if ttft_deadline_ms is not None:
                        fwd_body["ttft_deadline_ms"] = float(
                            ttft_deadline_ms)
                    req = _post_json(
                        rep.url + "/generate", fwd_body, self._timeout,
                        headers=fwd_headers,
                    )
                with req as resp:
                    if stream and not headers_sent:
                        handler.send_response(200)
                        handler.send_header("Content-Type",
                                            "text/event-stream")
                        handler.send_header(_trace.HEADER, tid)
                        handler.end_headers()
                        headers_sent = True
                        _sse_write(handler.wfile,
                                   {"meta": {"trace": tid}})
                    for ev in sse_events(resp):
                        if "token" in ev:
                            if t_first is None:
                                t_first = time.perf_counter()
                            tokens.append(ev["token"])
                            if stream:
                                _sse_write(handler.wfile,
                                           {"token": ev["token"]})
                                relayed += 1
                        elif ev.get("shed"):
                            # the replica shed this request at dequeue
                            # (TTFT deadline) — retriable, and the
                            # replica itself is healthy. Relay the
                            # in-band error when streaming; for a
                            # buffered client the 429 moment has not
                            # passed yet, so map it back to one.
                            ra = float(ev.get(
                                "retry_after_s",
                                _admission.MIN_RETRY_AFTER_S))
                            if stream:
                                metrics.default_registry().counter(
                                    "router/relayed_shed").incr()
                                _sse_write(handler.wfile, ev)
                            else:
                                self._reject(handler, headers_sent,
                                             "deadline_shed", ra, tid)
                            return
                        elif ev.get("done"):
                            finished = True
                            break
                if not finished:
                    # close-delimited stream ended without `done`: the
                    # replica died mid-decode
                    raise ConnectionError("stream ended before done")
            except urllib.error.HTTPError as e:
                # request-level rejection (validation): the replica is
                # fine — forward the error, do NOT mark down. Once SSE
                # headers (and possibly body bytes) went out, a second
                # send_response would corrupt the stream — report
                # in-band instead
                detail = e.read().decode(errors="replace")
                if e.code == 429 and not headers_sent:
                    # this replica's admission gate said no — another
                    # may still have room (the /load snapshot is a TTL
                    # cache; it can lag). Remember its drain estimate
                    # and try the next one.
                    try:
                        ra = float(json.loads(detail)["retry_after_s"])
                    except (json.JSONDecodeError, KeyError, TypeError,
                            ValueError):
                        ra = _admission.MIN_RETRY_AFTER_S
                    sat429.append(ra)
                    exclude.append(rep.idx)
                    continue
                if headers_sent:
                    _sse_write(handler.wfile,
                               {"error": detail, "retriable": False})
                else:
                    ReplicaServer._send_json(handler, e.code,
                                             {"error": detail},
                                             headers={_trace.HEADER: tid})
                return
            except _DEAD as e:
                self._mark_down(rep, str(e))
                exclude.append(rep.idx)
                if stream and relayed:
                    # tokens already left the building: the client must
                    # retry itself (same prompt re-runs from scratch)
                    _sse_write(handler.wfile,
                               {"error": "replica_died",
                                "retriable": True, "relayed": relayed})
                    return
                continue   # nothing delivered yet: transparent re-route
            finally:
                with self._lock:
                    self._inflight.pop(tid, None)
                self._account(rep, outstanding=-budget)
                self._publish()
            self._account(rep, served=1)
            self._publish()
            # client-observed SLO accounting: TTFT spans queueing,
            # placement, any re-routes and the primed hand-off; TPOT is
            # the steady-state inter-token rate after the first
            t_done = time.perf_counter()
            n = len(tokens)
            if t_first is not None:
                ttft_ms = (t_first - t_req) * 1e3
                tpot_ms = ((t_done - t_first) * 1e3 / (n - 1)
                           if n > 1 else None)
                self._slo.record(ttft_ms=ttft_ms, tpot_ms=tpot_ms)
                _trace.note_exemplar("router/ttft_ms", ttft_ms, tid)
            if _trace.active():
                _trace.event("router/done", trace=tid, replica=rep.idx,
                             tokens=n, rerouted=bool(exclude),
                             dur=t_done - t_req)
            if stream:
                _sse_write(handler.wfile,
                           {"done": True, "tokens": tokens,
                            "replica": rep.idx, "trace": tid})
            else:
                ReplicaServer._send_json(
                    handler, 200,
                    {"tokens": tokens, "replica": rep.idx, "trace": tid},
                    headers={_trace.HEADER: tid},
                )
            return


# -- blocking client (tests / bench / examples) ------------------------------
def request_generate(router_url: str, prompt, max_new_tokens: int,
                     stream: bool = False, timeout: float = 120.0,
                     priority: Optional[str] = None,
                     ttft_deadline_ms: Optional[float] = None) -> dict:
    """POST one generation to a Router (or directly to a ReplicaServer's
    /generate). Returns {"tokens": [...], "replica": idx|None,
    "ttft_s": seconds-to-first-token, "events": n, "trace": id|None —
    the session's X-Tfde-Trace id for /trace/<id> lookups}. Raises the
    underlying urllib error on transport failure (a pre-stream overload
    rejection surfaces as HTTPError 429 with Retry-After) and
    RuntimeError on an in-stream retriable error (a deadline-shed
    mid-stream reads "deadline_shed")."""
    url = router_url.rstrip("/")
    path = "/v1/generate" if "/generate" not in url else ""
    t0 = time.perf_counter()
    payload = {"prompt": list(np.asarray(prompt).tolist()),
               "max_new_tokens": int(max_new_tokens), "stream": True}
    if priority is not None:
        payload["priority"] = str(priority)
    if ttft_deadline_ms is not None:
        payload["ttft_deadline_ms"] = float(ttft_deadline_ms)
    tokens: list = []
    ttft = None
    replica = None
    trace_id = None
    n_events = 0
    with _post_json(url + path, payload, timeout) as resp:
        trace_id = resp.headers.get(_trace.HEADER)
        for ev in sse_events(resp):
            n_events += 1
            if "token" in ev:
                if ttft is None:
                    ttft = time.perf_counter() - t0
                tokens.append(ev["token"])
            elif "meta" in ev:
                trace_id = ev["meta"].get("trace", trace_id)
            elif "error" in ev:
                raise RuntimeError(ev["error"])
            elif ev.get("done"):
                replica = ev.get("replica")
                trace_id = ev.get("trace", trace_id)
                break
    return {"tokens": tokens, "replica": replica, "ttft_s": ttft,
            "events": n_events, "trace": trace_id}
