"""Sharding rules: pytree -> PartitionSpec mapping.

Where the reference relies on TF strategies to intercept variable creation and
place replicas (distributed_with_keras.py:51-58) or shard variables onto ps
jobs (tf2_mnist_distributed.py:189), the TPU-native design declares *where
each array lives* as a PartitionSpec over mesh axes and lets the XLA
partitioner insert the matching collectives.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def data_axes(mesh: Mesh) -> tuple:
    """The data-like mesh axes a batch dim splits over: ('data', 'fsdp') ∩
    mesh, size-1 axes dropped. Single source of truth for batch_spec and the
    pipeline's microbatch sharding (parallel/pipeline.py)."""
    return tuple(
        a for a in ("data", "fsdp")
        if a in mesh.axis_names and mesh.shape[a] > 1
    )


def batch_spec(mesh: Mesh, extra_dims: int = 0) -> P:
    """PartitionSpec for a [global_batch, ...] array: batch dim split over all
    data-like axes present in the mesh (data, then fsdp if present — FSDP
    shards the batch over both so that weight all-gathers amortize)."""
    axes = data_axes(mesh)
    if not axes:
        return P(*(None,) * (1 + extra_dims))
    return P(axes[0] if len(axes) == 1 else axes, *(None,) * extra_dims)


def _largest_divisible_dim(
    shape: Sequence[int], size: int, min_elems: int,
    eligible: Optional[Callable[[int], bool]] = None,
) -> Optional[int]:
    """Pick the largest dim divisible by `size`, if the array is big enough;
    `eligible(dim_index)` restricts the candidates (add_axis_to_spec uses it
    to skip already-sharded dims)."""
    total = 1
    for s in shape:
        total *= s
    if total < min_elems:
        return None
    best, best_size = None, 0
    for i, s in enumerate(shape):
        if eligible is not None and not eligible(i):
            continue
        if s % size == 0 and s > best_size:
            best, best_size = i, s
    return best


def shard_pytree_spec(
    tree: Any,
    mesh: Mesh,
    axis: str,
    min_elems: int = 2**14,
    rule: Optional[Callable[[tuple, Any], Optional[P]]] = None,
) -> Any:
    """Generic weight-sharding rule: for each leaf, shard its largest
    `axis_size`-divisible dimension over `axis`; small leaves stay replicated.

    This is the ZeRO/FSDP workhorse: applied to params for FSDP, or to
    optimizer state only for ZeRO-1 (the ParameterServerStrategy capability
    analog — sharded variable hosting, SURVEY.md §2b row 2).

    `rule(path, leaf) -> PartitionSpec | None` overrides per-leaf when given.
    """
    size = mesh.shape[axis]

    def leaf_spec(path, leaf):
        if rule is not None:
            r = rule(path, leaf)
            if r is not None:
                return r
        shape = getattr(leaf, "shape", ())
        if size <= 1 or not shape:
            return P()
        dim = _largest_divisible_dim(shape, size, min_elems)
        if dim is None:
            return P()
        spec = [None] * len(shape)
        spec[dim] = axis
        return P(*spec)

    return jax.tree_util.tree_map_with_path(leaf_spec, tree)


def add_axis_to_spec(
    spec: P, shape: Sequence[int], mesh: Mesh, axis: str,
    min_elems: int = 2**14,
) -> P:
    """Layer `axis` onto an existing PartitionSpec: shard the largest dim the
    spec leaves unsharded (divisible by the axis size; big-enough arrays
    only). The ZeRO-over-TP composition primitive — e.g. a Megatron qkv
    kernel P(None, 'tensor', None) gains 'data' on its embed dim for ZeRO-1
    optimizer-state sharding."""
    size = mesh.shape[axis]
    if size <= 1 or not shape:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for e in entries:
        # a mesh axis may map to at most one dimension: leave specs that
        # already use `axis` (possibly inside a tuple entry) untouched
        if e == axis or (isinstance(e, tuple) and axis in e):
            return spec
    best = _largest_divisible_dim(
        shape, size, min_elems, eligible=lambda i: entries[i] is None
    )
    if best is None:
        return spec
    entries[best] = axis
    return P(*entries)


def replicated_spec(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda _: P(), tree)
