"""Parallelism: sharding rules, strategies, and collectives.

The TPU-native replacement for the reference's `tf.distribute` strategy layer
(SURVEY.md §2c): every strategy is a set of PartitionSpecs over one device
mesh, compiled by XLA into ICI/DCN collectives.
"""

from tfde_tpu.parallel.strategies import (  # noqa: F401
    Strategy,
    MirroredStrategy,
    MultiWorkerMirroredStrategy,
    ParameterServerStrategy,
    FSDPStrategy,
    PipelineParallelStrategy,
    TensorParallelStrategy,
    SequenceParallelStrategy,
    ExpertParallelStrategy,
)
from tfde_tpu.parallel.sharding import (  # noqa: F401
    shard_pytree_spec,
    batch_spec,
    named_sharding,
)
from tfde_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    stack_stage_params,
)
from tfde_tpu.parallel.comms import (  # noqa: F401
    CommsConfig,
)
