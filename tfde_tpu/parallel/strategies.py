"""Distribution strategies — the reference's `tf.distribute` surface, TPU-native.

Reference strategy -> TPU-native mapping (SURVEY.md §2b/§2c):

- `MirroredStrategy` (mnist_keras_distributed.py:243): sync DP over the local
  chips. Mesh = {'data': n_local}; params replicated; batch split over 'data'.
- `MultiWorkerMirroredStrategy` (distributed_with_keras.py:16): sync DP over
  all chips of all hosts; identical shardings, the 'data' axis simply spans
  hosts — XLA routes the gradient `psum` over ICI within a slice and DCN
  across, replacing the RING/NCCL collective.
- `ParameterServerStrategy` (tf2_mnist_distributed.py:189,
  mnist_keras_distributed.py:241-243): async PS has no idiomatic TPU analog.
  We provide the same *capability* — sharded variable/optimizer-state hosting,
  role-aware bootstrap, restart tolerance — as **synchronous DP with ZeRO-1
  optimizer-state sharding** over the data axis. This is a documented semantic
  change (async -> sync); see SURVEY.md §7 "hard parts".
- `FSDPStrategy`: scale-up config from BASELINE.json (ViT-B/16 pjit FSDP) —
  params *and* optimizer state sharded over an 'fsdp' axis, all-gathered just
  in time by the partitioner.

A Strategy is deliberately thin: it owns (a) the mesh, (b) PartitionSpecs for
params / optimizer state / batch. The train step itself (training/step.py) is
strategy-agnostic — XLA's SPMD partitioner turns the same traced computation
into the right collectives for each sharding.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfde_tpu.parallel import comms as comms_lib
from tfde_tpu.parallel import sharding as shd
from tfde_tpu.parallel import zero as zero_lib
from tfde_tpu.runtime import mesh as mesh_lib


class Strategy:
    """Base: replicated params, batch split over data-like mesh axes.

    `grad_transport` selects the gradient-exchange wire format
    (parallel/comms.py): 'fp32' (default — the implicit SPMD psum,
    byte-identical to always) or 'int8' (blockwise-quantized all-reduce
    with error feedback); a CommsConfig tunes threshold/block/rounding.
    None defers to $TFDE_GRAD_TRANSPORT, then 'fp32'.

    `opt_sharding` selects the weight-update layout (parallel/zero.py):
    'replicated' (default — every replica holds full optimizer state and
    redoes the full update) or 'shard' (ZeRO-style: optimizer state and
    update sharded 1/N over the data axis, params all-gathered after).
    None defers to $TFDE_OPT_SHARDING, then 'replicated'. Warn-falls-back
    on ineligible meshes/strategies exactly like the comms knob.
    """

    def __init__(self, mesh: Optional[Mesh] = None, grad_transport=None,
                 opt_sharding=None):
        self._mesh = mesh
        self._comms = (
            comms_lib.resolve(grad_transport)
            if grad_transport is not None else None
        )
        self._opt_sharding = (
            zero_lib.resolve(opt_sharding)
            if opt_sharding is not None else None
        )

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self._mesh = self._default_mesh()
        return self._mesh

    @property
    def comms(self) -> "comms_lib.CommsConfig":
        """The gradient-transport config; resolved lazily so an unset knob
        reads $TFDE_GRAD_TRANSPORT at first use, not at import."""
        if self._comms is None:
            self._comms = comms_lib.resolve(None)
        return self._comms

    @comms.setter
    def comms(self, value) -> None:
        self._comms = comms_lib.resolve(value)

    @property
    def opt_sharding(self) -> str:
        """The weight-update sharding mode; resolved lazily so an unset
        knob reads $TFDE_OPT_SHARDING at first use, not at import."""
        if self._opt_sharding is None:
            self._opt_sharding = zero_lib.resolve(None)
        return self._opt_sharding

    @opt_sharding.setter
    def opt_sharding(self, value) -> None:
        self._opt_sharding = zero_lib.resolve(value)

    def _default_mesh(self) -> Mesh:
        return mesh_lib.data_parallel_mesh()

    # -- PartitionSpecs ------------------------------------------------------
    def params_spec(self, params: Any) -> Any:
        return shd.replicated_spec(params)

    def _opt_params_spec(self, params: Any) -> Any:
        """Specs used for params-shaped optimizer slots (mu/nu/trace).
        Defaults to the params' own specs; strategies that shard optimizer
        state differently from params (ZeRO-1 layered on TP) override."""
        return self.params_spec(params)

    def opt_state_spec(self, opt_state: Any, params: Any) -> Any:
        """Optimizer state follows params: any sub-tree of the optimizer state
        that is *structurally* a params tree (optax mu/nu/trace slots) gets the
        `_opt_params_spec` specs; everything else (counts, scalars)
        replicates."""
        pspec = self._opt_params_spec(params)
        ptreedef = jax.tree_util.tree_structure(params)

        def walk(node):
            if jax.tree_util.tree_structure(node) == ptreedef:
                return pspec
            if isinstance(node, tuple):  # includes namedtuples & optax chains
                mapped = [walk(c) for c in node]
                return type(node)(*mapped) if hasattr(node, "_fields") else tuple(mapped)
            if isinstance(node, dict):
                return {k: walk(v) for k, v in node.items()}
            if isinstance(node, list):
                return [walk(c) for c in node]
            return jax.tree_util.tree_map(lambda _: P(), node)

        return walk(opt_state)

    def batch_spec(self) -> P:
        return shd.batch_spec(self.mesh)

    # -- Shardings -----------------------------------------------------------
    def params_sharding(self, params: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            self.params_spec(params),
            is_leaf=lambda x: isinstance(x, P),
        )

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    @property
    def num_replicas(self) -> int:
        return self.mesh.devices.size

    @property
    def batch_divisor(self) -> int:
        """Global batch sizes must divide by this (the product of mesh axes
        the batch dim is split over)."""
        spec = self.batch_spec()
        first = spec[0] if len(spec) else None
        if first is None:
            return 1
        axes = first if isinstance(first, tuple) else (first,)
        size = 1
        for a in axes:
            size *= self.mesh.shape[a]
        return size

    def describe(self) -> str:
        return f"{type(self).__name__}(mesh={dict(self.mesh.shape)})"


class MirroredStrategy(Strategy):
    """Single-host sync DP over local devices (mnist_keras:243 analog)."""

    def _default_mesh(self) -> Mesh:
        return mesh_lib.local_mirrored_mesh()


class MultiWorkerMirroredStrategy(Strategy):
    """Sync DP over every chip in the cluster (distributed_with_keras.py:16).

    Construct *after* `runtime.bootstrap()` so jax.devices() spans all hosts —
    the analog of the reference's rule that the strategy be built before other
    TF ops (distributed_with_keras.py:1-4,16), but without the ordering trap.
    """


@dataclasses.dataclass
class _ZeroConfig:
    min_elems: int = 2**14


class ParameterServerStrategy(Strategy):
    """PS capability, sync semantics: ZeRO-1 sharded optimizer state.

    The reference hosts variables on ps tasks and lets workers fetch/update
    them over gRPC (tf2_mnist:189; device filters mnist_keras:165-189). Here
    the 'variable hosting' is the optimizer state sharded over the data axis:
    each replica owns 1/N of mu/nu/etc., XLA reduce-scatters grads into the
    owning shard and all-gathers fresh params — same memory-scaling benefit,
    synchronous math. Params stay replicated (ZeRO-1).
    """

    def __init__(self, mesh: Optional[Mesh] = None, min_shard_elems: int = 2**14,
                 grad_transport=None, opt_sharding=None):
        super().__init__(mesh, grad_transport=grad_transport,
                         opt_sharding=opt_sharding)
        self._zero = _ZeroConfig(min_shard_elems)

    def opt_state_spec(self, opt_state: Any, params: Any) -> Any:
        return shd.shard_pytree_spec(
            opt_state, self.mesh, "data", min_elems=self._zero.min_elems
        )


def _path_names(path) -> tuple:
    """jax key-path -> tuple of string names."""
    names = []
    for k in path:
        if hasattr(k, "key"):
            names.append(str(k.key))
        elif hasattr(k, "name"):
            names.append(str(k.name))
        elif hasattr(k, "idx"):
            names.append(str(k.idx))
        else:
            names.append(str(k))
    return tuple(names)


_TP_COLUMN = ("query", "key", "value", "fc1", "gate")  # shard output dim(s)
_TP_ROW = ("out", "fc2")                        # shard input dim(s)


def _megatron_tensor_dim(module: str, kind: str, shape, tsize: int,
                         offset: int = 0):
    """Dim index to split over 'tensor' per the Megatron column/row rules,
    or None. `offset` skips leading stacking dims (the [S, L] prefix of
    pipelined stage leaves) — one rule shared by the 2D and 3D strategies
    so they cannot drift."""
    if tsize <= 1:
        return None
    body = shape[offset:]
    if module == "qkv":
        # fused projection (transformer.fused_qkv): kernel
        # [embed, 3, heads, hd] / bias [3, heads, hd] — still
        # column-parallel, heads dim split over 'tensor'
        if kind == "kernel" and len(body) >= 3 and body[2] % tsize == 0:
            return offset + 2
        if kind == "bias" and len(body) >= 2 and body[1] % tsize == 0:
            return offset + 1
        return None
    if module in _TP_COLUMN:
        # qkv [embed, heads, hd] / fc1 [embed, ffn]: split dim 1
        if kind == "kernel" and len(body) >= 2 and body[1] % tsize == 0:
            return offset + 1
        # qkv bias [heads, hd] / fc1 bias [ffn]: split dim 0
        if kind == "bias" and len(body) >= 1 and body[0] % tsize == 0:
            return offset
        return None
    # out [heads, hd, embed] / fc2 [ffn, embed]: split dim 0
    if module in _TP_ROW and kind == "kernel" \
            and len(body) >= 1 and body[0] % tsize == 0:
        return offset
    return None


class TensorParallelStrategy(Strategy):
    """Megatron-style tensor parallelism over the 'tensor' mesh axis.

    Scale-up scope beyond the reference's DP-only surface (SURVEY.md §2c:
    "TP: absent"), built for the transformer configs. The sharding rules
    match the weight shapes models/transformer.py commits to:

    - q/k/v kernels [embed, heads, head_dim]: column-parallel — heads split
      over 'tensor'; biases [heads, head_dim] follow.
    - attention out kernel [heads, head_dim, embed]: row-parallel — the
      contraction dims split, XLA inserts one psum after the projection.
    - mlp fc1 [embed, ffn]: column-parallel; bias follows. fc2 [ffn, embed]:
      row-parallel -> second psum. A swiglu 'gate' [embed, ffn] is
      column-parallel like fc1 — both outputs carry the same ffn shard, so
      the elementwise gating needs no collective.
    - everything else (LayerNorms, embeddings, heads, conv stems) replicates.

    Combined with the activation constraints the models already carry
    (parallel/axes.constrain over 'tensor'), each transformer block runs at
    1/T the weight memory and exactly two reduction collectives — both over
    the innermost (ICI-fastest) mesh axis, per runtime/mesh.AXIS_ORDER.

    `extra_rules`: optional [(predicate(names)->bool, spec_fn(shape)->P)]
    applied before the built-ins, for model-specific overrides.

    `zero1=True` composes ZeRO-1 on top: params-shaped optimizer slots
    (Adam mu/nu) additionally shard their largest TP-unsharded dim over
    'data' (sharding.add_axis_to_spec) — the Megatron+ZeRO combination,
    same memory story as ParameterServerStrategy but under a TP layout.
    """

    _COLUMN = _TP_COLUMN
    _ROW = _TP_ROW

    def __init__(self, mesh: Optional[Mesh] = None, data: int = 1,
                 extra_rules=(), zero1: bool = False,
                 min_shard_elems: int = 2**14, grad_transport=None,
                 opt_sharding=None):
        self._data = data
        self._extra = tuple(extra_rules)
        self._zero1 = zero1
        self._min = min_shard_elems
        super().__init__(mesh, grad_transport=grad_transport,
                         opt_sharding=opt_sharding)

    def _default_mesh(self) -> Mesh:
        return mesh_lib.make_mesh({"data": self._data, "tensor": -1})

    def params_spec(self, params: Any) -> Any:
        tsize = self.mesh.shape["tensor"]

        def leaf_spec(path, leaf):
            names = _path_names(path)
            shape = getattr(leaf, "shape", ())
            for pred, spec_fn in self._extra:
                if pred(names):
                    return spec_fn(shape)
            if tsize <= 1 or not shape:
                return P()
            module = names[-2] if len(names) >= 2 else ""
            kind = names[-1]
            dim = _megatron_tensor_dim(module, kind, shape, tsize)
            if dim is None:
                return P()
            spec = [None] * len(shape)
            spec[dim] = "tensor"
            return P(*spec)

        return jax.tree_util.tree_map_with_path(leaf_spec, params)

    def _opt_params_spec(self, params: Any) -> Any:
        pspec = self.params_spec(params)
        if not self._zero1:
            return pspec
        return jax.tree_util.tree_map(
            lambda sp, leaf: shd.add_axis_to_spec(
                sp, getattr(leaf, "shape", ()), self.mesh, "data",
                min_elems=self._min,
            ),
            pspec, params,
            is_leaf=lambda x: isinstance(x, P),
        )


class ExpertParallelStrategy(Strategy):
    """Expert parallelism: MoE expert weights shard over the 'expert' axis.

    Scale-up scope beyond the reference (SURVEY.md §2c: "EP: absent").
    Expert-stacked params ([num_experts, ...] leaves named ``experts_*`` by
    models/moe.MoEMlp) split their leading dim across the axis; everything
    else (attention, norms, router, dense blocks) replicates, and the batch
    still splits over 'data'. The dispatch/combine einsums in the MoE layer
    cross the token/expert sharding boundary, which XLA lowers to the
    all-to-all-style exchange over ICI.
    """

    def __init__(self, mesh: Optional[Mesh] = None, data: int = 1,
                 grad_transport=None, opt_sharding=None):
        self._data = data
        super().__init__(mesh, grad_transport=grad_transport,
                         opt_sharding=opt_sharding)

    def _default_mesh(self) -> Mesh:
        return mesh_lib.make_mesh({"data": self._data, "expert": -1})

    def params_spec(self, params: Any) -> Any:
        esize = self.mesh.shape["expert"]

        def leaf_spec(path, leaf):
            names = _path_names(path)
            shape = getattr(leaf, "shape", ())
            if (
                esize > 1
                and names
                and names[-1].startswith("experts_")
                and shape
                and shape[0] % esize == 0
            ):
                return P("expert", *(None,) * (len(shape) - 1))
            return P()

        return jax.tree_util.tree_map_with_path(leaf_spec, params)


class SequenceParallelStrategy(Strategy):
    """Sequence/context parallelism: activations shard over 'seq'.

    Long-context scope beyond the reference (SURVEY.md §5 "long-context:
    entirely absent"). Params replicate (inherited); what changes is the
    activation layout — the models' `constrain(x, batch, 'seq')` annotations
    split the sequence dim across the ring, and ops/attention auto-dispatches
    to ring attention (ops/ring_attention.py), whose KV rotation rides
    neighbor ICI links. Max context length scales linearly with the 'seq'
    axis size, which must divide the sequence length evenly.
    """

    def __init__(self, mesh: Optional[Mesh] = None, data: int = 1,
                 grad_transport=None, opt_sharding=None):
        self._data = data
        super().__init__(mesh, grad_transport=grad_transport,
                         opt_sharding=opt_sharding)

    def _default_mesh(self) -> Mesh:
        return mesh_lib.make_mesh({"data": self._data, "seq": -1})


class PipelineParallelStrategy(Strategy):
    """Pipeline parallelism over the 'pipe' mesh axis (GPipe schedule).

    Scale-up scope beyond the reference (SURVEY.md §2c: "Pipeline parallel:
    absent"). Pairs with models/pipelined.PipelinedLM: the model's
    stage-stacked params ([num_stages, layers_per_stage, ...] leaves under
    the top-level 'stages' key) shard their leading dim over 'pipe' — each
    pipe rank holds exactly its stage's weights — while the embedding / head
    / final-norm params replicate. The batch still splits over 'data'
    (inherited batch_spec ignores 'pipe'), so DP composes with pipelining on
    a {'data': D, 'pipe': S} mesh; microbatches shard over 'data' inside
    `pipeline_apply`.

    The optimizer state follows the params (inherited opt_state_spec walk),
    so each pipe rank also owns only its stage's Adam moments.

    `tensor > 1` composes Megatron tensor parallelism INSIDE the stages
    (dp x pp x tp, 3D): stage-stacked block weights additionally shard
    their column/row dims over a 'tensor' axis — the same rules as
    TensorParallelStrategy, offset by the [num_stages, layers_per_stage]
    leading dims. Requires the model to run the pipe in partial-manual
    ('auto') mode (models/pipelined.PipelinedLM auto-selects it when the
    mesh has a tensor axis) so the automatic partitioner handles the
    tensor collectives inside the ring.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        data: int = 1,
        pipe: Optional[int] = None,
        tensor: int = 1,
        seq: int = 1,
        grad_transport=None,
        opt_sharding=None,
    ):
        self._data = data
        self._pipe = pipe
        self._tensor = tensor
        self._seq = seq
        super().__init__(mesh, grad_transport=grad_transport,
                         opt_sharding=opt_sharding)

    def _default_mesh(self) -> Mesh:
        axes = {"data": self._data, "pipe": self._pipe or -1}
        if self._tensor > 1:
            axes["tensor"] = self._tensor
        if self._seq > 1:
            axes["seq"] = self._seq
        if self._pipe is not None:
            # explicit stage count: use the first data*pipe*tensor*seq
            # devices so the mesh matches the model's num_stages even when
            # the host has more
            devices = jax.devices()[
                : self._data * self._pipe * self._tensor * self._seq
            ]
            return mesh_lib.make_mesh(axes, devices)
        return mesh_lib.make_mesh(axes)

    def params_spec(self, params: Any) -> Any:
        psize = self.mesh.shape["pipe"]
        tsize = self.mesh.shape.get("tensor", 1)
        if tsize > 1 and psize <= 1:
            raise ValueError(
                "PipelineParallelStrategy with a 'tensor' axis but pipe<=1 "
                "would replicate every weight across the tensor devices — "
                "use TensorParallelStrategy for TP without pipelining"
            )
        if self.mesh.shape.get("seq", 1) > 1 and tsize > 1:
            # pp x sp runs in the FULLY-manual ring (the per-shard ring
            # body inlines into the same flat manual region); the
            # partial-manual mode tensor>1 needs would nest manual
            # regions, which does not lower (Shardy, jax 0.9)
            raise ValueError(
                "pp x sp x tp does not compose: a 'seq' axis needs the "
                "fully-manual pipe, a 'tensor' axis the partial-manual "
                "one — drop either tensor or seq"
            )

        def leaf_spec(path, leaf):
            names = _path_names(path)
            shape = getattr(leaf, "shape", ())
            if not (psize > 1 and "stages" in names and shape
                    and shape[0] == psize):
                return P()
            spec = ["pipe"] + [None] * (len(shape) - 1)
            if tsize > 1 and len(names) >= 2:
                # the shared Megatron rules, offset past [S, L]
                dim = _megatron_tensor_dim(
                    names[-2], names[-1], shape, tsize, offset=2
                )
                if dim is not None:
                    spec[dim] = "tensor"
            return P(*spec)

        return jax.tree_util.tree_map_with_path(leaf_spec, params)


class FSDPStrategy(Strategy):
    """Fully-sharded DP: params + opt state sharded over 'fsdp' axis.

    BASELINE.json configs[3] ("ImageNet ViT-B/16 (pjit FSDP over ICI mesh)").
    Batch is split over data×fsdp (see sharding.batch_spec) so the per-step
    weight all-gather amortizes over a larger local batch.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        data: int = 1,
        min_shard_elems: int = 2**10,
        grad_transport=None,
        opt_sharding=None,
    ):
        self._data = data
        self._min = min_shard_elems
        super().__init__(mesh, grad_transport=grad_transport,
                         opt_sharding=opt_sharding)

    def _default_mesh(self) -> Mesh:
        return mesh_lib.make_mesh({"data": self._data, "fsdp": -1})

    def params_spec(self, params: Any) -> Any:
        return shd.shard_pytree_spec(params, self.mesh, "fsdp", min_elems=self._min)

    def opt_state_spec(self, opt_state: Any, params: Any) -> Any:
        return shd.shard_pytree_spec(opt_state, self.mesh, "fsdp", min_elems=self._min)
