"""ZeRO-style weight-update sharding (arXiv 2004.13336).

Data-parallel training replicates the optimizer state: every replica holds
a full copy of Adam's mu/nu (2x params in fp32) and every replica redoes
the identical weight update. "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" shards both across the data replicas
instead: reduce-scatter the gradients so each replica owns 1/N of them,
run the optimizer on that 1/N slice only (optimizer state allocated for
the slice alone), then all-gather the *updated parameters* — the same
wire volume as the all-reduce the update replaced, but 1/N the optimizer
memory and 1/N the update flops.

This module owns the chunked layout behind the `opt_sharding=
'replicated'|'shard'` knob (strategies / RunConfig / $TFDE_OPT_SHARDING):

- `build_layout` flattens the params like `comms.pack` into two segments:
  "big" leaves (>= the comms config's min_elems — the same split the int8
  transport uses, so the int8 reduce-scatter's owner chunks ARE the update
  chunks) and "small" leaves (biases/norms riding the fp32 sidecar). Both
  segments pad to an nshards multiple; the big segment pads to the int8
  quantum (nshards x block) even under fp32 transport, so chunk boundaries
  are transport-independent and a sharded checkpoint written under fp32
  restores bit-identically under int8 and vice versa.
- `pack_params` / `unpack_params` move between the params tree and the
  {packed_big: [N, Cb], packed_small: [N, Cs]} chunk tree; the optimizer
  state is simply `tx.init` of the packed tree, so its params-shaped slots
  (mu/nu/trace/ema) are born [N, C] and shard row-wise over the data axis
  (`opt_state_spec`) — genuinely distributed arrays that Orbax
  checkpoints shard-by-shard.
- `pack_opt_state` / `unpack_opt_state` convert a replicated optimizer
  state to the packed form and back (checkpoint cross-compat both ways).

Correctness contract: the packed chunk update is bit-identical to the
replicated per-leaf update for ELEMENTWISE transforms (sgd, momentum,
adam, adamw without a mask, param-EMA) — the update of element i depends
only on (g_i, state_i, p_i), so slicing commutes with updating. Structure-
sensitive transforms (optax.masked / `training.optimizers.decay_mask`,
anything keyed on leaf paths or shapes) would silently see the packed
{packed_big, packed_small} tree instead of the params tree; `packable`
detects the masked case from the abstract state and init_state
warn-falls-back to replicated, the rest is a documented limitation
(README "Weight-update sharding").
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tfde_tpu import knobs
from tfde_tpu.parallel import comms as comms_lib

log = logging.getLogger(__name__)

#: env default for the knob — tools/tier1.sh forwards it so the whole
#: tier-1 suite can re-run with sharded weight updates in one command:
#:   TFDE_OPT_SHARDING=shard tools/tier1.sh
ENV_OPT_SHARDING = "TFDE_OPT_SHARDING"

MODES = ("replicated", "shard")

#: keys of the packed chunk tree. Deliberately distinctive (not "big"/
#: "small") so checkpoint metadata sniffing cannot false-match a user dict.
BIG = "packed_big"
SMALL = "packed_small"


def resolve(value: Any = None) -> str:
    """Sugar -> mode string: a mode passes through, None defers to
    $TFDE_OPT_SHARDING (unset = 'replicated', so existing configs are
    byte-identical)."""
    if value is None:
        # env-derived: a typo'd mode warns once and runs 'replicated'
        # (tfde_tpu/knobs.py); explicit call-site values still raise below.
        value = knobs.env_choice(ENV_OPT_SHARDING) or "replicated"
    if isinstance(value, str):
        if value not in MODES:
            raise ValueError(
                f"opt_sharding must be one of {MODES}, got {value!r}"
            )
        return value
    raise TypeError(
        f"opt_sharding must be None or str, got {type(value).__name__}"
    )


# -- the chunked layout -------------------------------------------------------
def _size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


@dataclasses.dataclass(frozen=True)
class Layout:
    """Static description of the packed two-segment layout. Hashable (all
    tuple/int fields + a treedef) so it can ride `TrainState.opt_layout`
    as a non-pytree (static) field through jit."""

    nshards: int
    block: int
    treedef: Any            # params treedef (jax treedefs hash/compare)
    shapes: Tuple[tuple, ...]   # per-leaf shapes, tree_flatten order
    dtypes: Tuple[str, ...]     # per-leaf dtype names
    mask: Tuple[bool, ...]      # True = big segment (comms.compress_mask)
    padded_big: int             # big segment length, quantum-padded
    padded_small: int           # small segment length, nshards-padded

    @property
    def chunk_big(self) -> int:
        return self.padded_big // self.nshards

    @property
    def chunk_small(self) -> int:
        return self.padded_small // self.nshards

    @property
    def total_big(self) -> int:
        return sum(_size(s) for s, m in zip(self.shapes, self.mask) if m)

    @property
    def total_small(self) -> int:
        return sum(_size(s) for s, m in zip(self.shapes, self.mask) if not m)


def build_layout(params: Any, ccfg: "comms_lib.CommsConfig",
                 nshards: int) -> Layout:
    """Layout for `params` (concrete or abstract) on an `nshards`-way data
    axis. The big/small split reuses the comms config's min_elems so the
    int8 transport's reduce-scatter chunks are exactly the update chunks;
    the big segment pads to the int8 quantum (nshards x block) under BOTH
    transports, making the layout — and therefore sharded checkpoints —
    transport-independent."""
    if nshards < 2:
        raise ValueError(f"opt_sharding='shard' needs >= 2 shards, got {nshards}")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    mask = tuple(
        bool(m) for m in jax.tree_util.tree_leaves(
            comms_lib.compress_mask(params, ccfg)
        )
    )
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(jnp.dtype(l.dtype).name for l in leaves)
    total_big = sum(_size(s) for s, m in zip(shapes, mask) if m)
    total_small = sum(_size(s) for s, m in zip(shapes, mask) if not m)
    quantum = nshards * ccfg.block
    padded_big = -(-total_big // quantum) * quantum if total_big else 0
    padded_small = -(-total_small // nshards) * nshards if total_small else 0
    return Layout(
        nshards=nshards, block=ccfg.block, treedef=treedef,
        shapes=shapes, dtypes=dtypes, mask=mask,
        padded_big=padded_big, padded_small=padded_small,
    )


def _pack_pad(leaves: Sequence[jax.Array], padded: int) -> jax.Array:
    """comms.pack + zero-pad to the segment length."""
    vec, _ = comms_lib.pack(list(leaves))
    if vec.shape[0] != padded:
        vec = jnp.pad(vec, (0, padded - vec.shape[0]))
    return vec


def segment_vectors(params: Any, layout: Layout) -> Tuple[jax.Array, jax.Array]:
    """(big [padded_big], small [padded_small]) fp32 segment vectors."""
    leaves = jax.tree_util.tree_leaves(params)
    big = [l for l, m in zip(leaves, layout.mask) if m]
    small = [l for l, m in zip(leaves, layout.mask) if not m]
    return (_pack_pad(big, layout.padded_big),
            _pack_pad(small, layout.padded_small))


def pack_params(params: Any, layout: Layout) -> dict:
    """Params tree -> {packed_big: [N, Cb], packed_small: [N, Cs]} fp32.
    Row i is replica i's owned chunk."""
    bigv, smallv = segment_vectors(params, layout)
    return {
        BIG: bigv.reshape(layout.nshards, layout.chunk_big),
        SMALL: smallv.reshape(layout.nshards, layout.chunk_small),
    }


def unpack_params(big_vec: jax.Array, small_vec: jax.Array,
                  layout: Layout) -> Any:
    """Segment vectors -> params tree (original shapes/dtypes; padding
    dropped)."""
    big_shapes = [s for s, m in zip(layout.shapes, layout.mask) if m]
    small_shapes = [s for s, m in zip(layout.shapes, layout.mask) if not m]
    big = comms_lib.unpack(big_vec, big_shapes)
    small = comms_lib.unpack(small_vec, small_shapes)
    out, bi, si = [], 0, 0
    for m, dt in zip(layout.mask, layout.dtypes):
        if m:
            out.append(big[bi].astype(dt))
            bi += 1
        else:
            out.append(small[si].astype(dt))
            si += 1
    return jax.tree_util.tree_unflatten(layout.treedef, out)


def unpack_packed(packed: dict, layout: Layout) -> Any:
    return unpack_params(
        jnp.asarray(packed[BIG]).reshape(-1),
        jnp.asarray(packed[SMALL]).reshape(-1),
        layout,
    )


def with_nshards(layout: Layout, nshards: int) -> Layout:
    """The same params packed over a *different* shard count: identical
    treedef/shapes/mask/block (the big/small split and the quantum unit are
    properties of the params + comms config, not of the world size), with
    the segment paddings recomputed for `nshards`. This is how a reader
    reconstructs the layout an M-way writer used from its own N-way layout
    — the elastic cross-world checkpoint bridge (checkpoint/manager.py).
    Accepts nshards=1 (a one-row packed form) so M->1 relayouts stay
    expressible even though training itself falls back to replicated
    below 2 shards."""
    if nshards < 1:
        raise ValueError(f"nshards must be >= 1, got {nshards}")
    quantum = nshards * layout.block
    total_big = layout.total_big
    total_small = layout.total_small
    return dataclasses.replace(
        layout,
        nshards=nshards,
        padded_big=-(-total_big // quantum) * quantum if total_big else 0,
        padded_small=(-(-total_small // nshards) * nshards
                      if total_small else 0),
    )


# -- optimizer-state conversion (checkpoint cross-compat) ---------------------
def _walk(node, match, rebuild):
    if match(node):
        return rebuild(node)
    if isinstance(node, tuple) and hasattr(node, "_fields"):
        return type(node)(*[_walk(c, match, rebuild) for c in node])
    if isinstance(node, tuple):
        return tuple(_walk(c, match, rebuild) for c in node)
    if isinstance(node, list):
        return [_walk(c, match, rebuild) for c in node]
    if isinstance(node, dict):
        return {k: _walk(v, match, rebuild) for k, v in node.items()}
    return node


def _is_packed_node(node) -> bool:
    return isinstance(node, dict) and set(node.keys()) == {BIG, SMALL}


def pack_opt_state(opt_state: Any, layout: Layout) -> Any:
    """Replicated optimizer state -> packed form: every params-congruent
    subtree (optax mu/nu/trace/ema slots) becomes its packed chunk tree;
    scalars (counts) pass through. Exact inverse of `unpack_opt_state`."""

    def match(node):
        try:
            return jax.tree_util.tree_structure(node) == layout.treedef
        except Exception:
            return False

    return _walk(opt_state, match, lambda n: pack_params(n, layout))


def unpack_opt_state(opt_state: Any, layout: Layout) -> Any:
    """Packed optimizer state -> replicated per-leaf form."""
    return _walk(opt_state, _is_packed_node,
                 lambda n: unpack_packed(n, layout))


def relayout_opt_state(opt_state: Any, from_layout: Layout,
                       to_layout: Layout) -> Any:
    """Re-chunk a packed optimizer state from one shard count to another
    (M-way checkpoint -> N-way mesh, both directions). Pure reshapes —
    unpack to the per-leaf form under the writer's layout, re-pack under
    the reader's — so the payload values are bit-exact; only the zero
    padding at the segment tails differs."""
    if (from_layout.treedef != to_layout.treedef
            or from_layout.shapes != to_layout.shapes
            or from_layout.mask != to_layout.mask):
        raise ValueError(
            "relayout_opt_state needs layouts over the same params "
            "(treedef/shapes/segment mask must match; only nshards may "
            "differ)"
        )
    return pack_opt_state(unpack_opt_state(opt_state, from_layout), to_layout)


def packable(abstract_opt_state: Any) -> bool:
    """False when the optimizer state contains an optax MaskedState — the
    mask function was evaluated against the params TREE, so re-initializing
    on the packed {packed_big, packed_small} tree would silently change
    which elements the inner transform sees. (Other structure-sensitive
    transforms cannot be detected from the state; see the module
    docstring.)"""
    bad: List[str] = []

    def scan(node):
        if type(node).__name__ == "MaskedState":
            bad.append(type(node).__name__)
        if isinstance(node, (tuple, list)):
            for c in node:
                scan(c)
        elif isinstance(node, dict):
            for c in node.values():
                scan(c)

    scan(abstract_opt_state)
    return not bad


# -- sharding + eligibility ---------------------------------------------------
def opt_state_spec(opt_state: Any, axis: str, nshards: int) -> Any:
    """PartitionSpec tree for a packed optimizer state: [N, C] chunk leaves
    shard row-wise over the data axis, scalars (counts) replicate."""
    return jax.tree_util.tree_map(
        lambda l: (
            P(axis)
            if getattr(l, "ndim", 0) >= 1 and l.shape[0] == nshards
            else P()
        ),
        opt_state,
    )


def eligible_axis(strategy, abstract_params: Any) -> Optional[str]:
    """The data axis the sharded update runs over, or None (with a warning)
    when the mesh/strategy is ineligible — the comms-style warn-fallback:
    needs a pure-DP mesh (exactly one data axis, no model axes > 1, same
    rule as the int8 exchange) AND fully replicated params (the packed
    chunks slice a replica-identical param vector; FSDP/TP layouts are
    already sharded and keep their own optimizer layout)."""
    mesh = strategy.mesh
    axis = comms_lib.data_axis(mesh)
    if axis is None or mesh.shape[axis] < 2:
        log.warning(
            "opt_sharding='shard' needs a pure-DP mesh with >= 2 data "
            "shards; mesh %s is not — falling back to replicated",
            dict(mesh.shape),
        )
        return None
    specs = jax.tree_util.tree_leaves(
        strategy.params_spec(abstract_params),
        is_leaf=lambda x: isinstance(x, P),
    )
    if any(any(e is not None for e in tuple(s)) for s in specs):
        log.warning(
            "opt_sharding='shard' needs replicated params; strategy %s "
            "shards them — falling back to replicated",
            type(strategy).__name__,
        )
        return None
    return axis


# -- accounting (opt/* gauges, bench) -----------------------------------------
def state_bytes(opt_state: Any, layout: Optional[Layout] = None) -> float:
    """Per-device optimizer-state bytes. With a layout, [N, C] chunk leaves
    count 1/N (each device holds one row); without, everything is
    replicated and counts in full."""
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        shape = tuple(getattr(leaf, "shape", ()))
        n = _size(shape) * jnp.dtype(leaf.dtype).itemsize
        if (layout is not None and shape
                and shape[0] == layout.nshards):
            n /= layout.nshards
        total += n
    return total


def measured_state_bytes(opt_state: Any) -> float:
    """Per-device optimizer-state bytes MEASURED from the committed arrays
    (max over devices of the shard bytes each actually holds, via
    memwatch.device_bytes) rather than derived from shapes. Returns 0.0
    for abstract/uncommitted leaves (callers fall back to the analytic
    state_bytes). The two should agree within padding; a larger gap is a
    sharding bug worth an alarm."""
    from tfde_tpu.observability import memwatch

    try:
        return float(memwatch.device_bytes(opt_state))
    except Exception:  # noqa: BLE001 — accounting must not break the step
        return 0.0


def param_gather_bytes(layout: Optional[Layout]) -> float:
    """Per-device wire bytes of the trailing param all-gather (ring cost:
    (N-1)/N per payload byte; the payload is both fp32 segments plus one
    grad-norm scalar per shard)."""
    if layout is None:
        return 0.0
    n = layout.nshards
    payload = 4.0 * (layout.padded_big + layout.padded_small + n)
    return (n - 1) / n * payload
