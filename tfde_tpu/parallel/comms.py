"""Communication-efficient gradient exchange: quantized all-reduce with
error feedback (EQuARX-style, arxiv 2506.17615).

Every DP strategy in parallel/strategies.py exchanges gradients at full
fp32 width — the implicit `psum` the SPMD partitioner inserts moves
~8 bytes/param per step over a ring, which dominates step time on DCN-heavy
meshes (MultiWorkerMirroredStrategy spanning hosts). This module provides
the int8 transport behind the `grad_transport='fp32'|'int8'` knob:

1. the local per-device gradient contribution (plus the error-feedback
   residual carried in `TrainState.comm_residual`) is flattened, packed
   into ONE buffer, and blockwise absmax-quantized to int8 against a
   *shared* per-block scale (`pmax` of the local absmaxes — tiny fp32
   collective, 4/block bytes per element);
2. the int8 payload reduce-scatters over the data axis (`psum_scatter`;
   the int32 accumulator is exact: 127 x nshards fits easily);
3. each device dequantizes the partial sums of its owned chunk with the
   shared scales — exact, because every device quantized against the same
   scale — and re-quantizes them blockwise to int8;
4. the re-quantized chunks and their scales all-gather back, so every
   device reconstructs the *identical* averaged gradient (bit-equal across
   the ring — replicas cannot drift).

Total wire traffic: ~2 bytes/param (reduce-scatter + all-gather, both
int8) + ~8/block bytes of scales, vs ~8 bytes/param for the fp32 ring —
a >=70% cut, reported by `comm_bytes` and the `comm/*` gauges.

Error feedback: quantization error does not vanish, it is *carried*. Each
device keeps the part of its own contribution the quantizer dropped
(input-side error, plus the re-quantization error of the chunk it owns)
in `TrainState.comm_residual` and re-injects it into the next step's
transmission — the compressed SGD trajectory then tracks the fp32 oracle
(tests/test_comms.py asserts loss-trajectory parity on MNIST). The
residual is per-device state: it rides through jit as a nominally
replicated pytree whose per-device contents differ, which is safe because
it only ever re-enters this exchange (the exchange output is what touches
params, and that is bit-identical across devices). Quantization bias is
killed separately by stochastic rounding (ops/quant.py
`stochastic_round`), on by default.

Small leaves (< `min_elems`) skip quantization: their scale metadata would
cost more than the payload saves. They ride a single packed fp32 `psum`
together with the step's scalars (loss/metrics/weights), so the whole
exchange is a fixed five collectives regardless of model structure —
tests/test_comms.py pins the count from the lowered HLO.

Implemented with `utils/compat.shard_map` so the same code runs on old
(check_rep/auto) and new (check_vma/axis_names) jax. The fp32 default is a
true no-op: training/step.py does not even import this module's exchange
into the traced program, and the jaxpr is bit-identical to the
pre-compression step.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tfde_tpu import knobs
from tfde_tpu.ops import quant as quant_lib
from tfde_tpu.parallel import sharding as shd

log = logging.getLogger(__name__)

#: env default for the transport knob — tools/tier1.sh forwards it so the
#: whole tier-1 suite can re-run under int8 transport in one command:
#:   TFDE_GRAD_TRANSPORT=int8 tools/tier1.sh
ENV_TRANSPORT = "TFDE_GRAD_TRANSPORT"

TRANSPORTS = ("fp32", "int8")


@dataclasses.dataclass(frozen=True)
class CommsConfig:
    """Gradient-transport knobs (strategy `grad_transport=` /
    RunConfig.grad_transport sugar resolves to this)."""

    #: 'fp32' = the implicit SPMD psum (today's path, byte-identical);
    #: 'int8' = the quantized exchange above
    transport: str = "fp32"
    #: per-leaf size threshold: leaves with fewer elements stay fp32
    #: (biases/norms — scale metadata would outweigh the payload saving)
    min_elems: int = 2048
    #: quantization block: one shared fp32 scale per `block` elements
    block: int = 256
    #: stochastic rounding (unbiased in expectation; deterministic under
    #: the step rng) — nearest rounding would bias the EWMA the error
    #: feedback has to clean up
    stochastic: bool = True

    def __post_init__(self):
        if self.transport not in TRANSPORTS:
            raise ValueError(
                f"grad_transport must be one of {TRANSPORTS}, "
                f"got {self.transport!r}"
            )
        if self.block < 1:
            raise ValueError("block must be >= 1")
        if self.min_elems < 0:
            raise ValueError("min_elems must be >= 0")


def resolve(value: Any = None) -> CommsConfig:
    """Sugar -> CommsConfig: a CommsConfig passes through, a transport
    string selects defaults, None defers to $TFDE_GRAD_TRANSPORT (unset =
    'fp32', so existing configs are byte-identical)."""
    if isinstance(value, CommsConfig):
        return value
    if value is None:
        # env-derived: a typo'd transport warns once and runs fp32
        # (tfde_tpu/knobs.py); explicit call-site values still raise in
        # CommsConfig.__post_init__.
        value = knobs.env_choice(ENV_TRANSPORT) or "fp32"
    if isinstance(value, str):
        return CommsConfig(transport=value)
    raise TypeError(
        f"grad_transport must be None/str/CommsConfig, "
        f"got {type(value).__name__}"
    )


# -- mesh eligibility ---------------------------------------------------------
def data_axis(mesh) -> Optional[str]:
    """The single data-like axis the int8 exchange runs over, or None when
    the mesh is not eligible (no data axis, or model axes > 1 — the
    exchange assumes replicated params, i.e. pure-DP meshes)."""
    daxes = shd.data_axes(mesh)
    if len(daxes) != 1:
        return None
    for a in mesh.axis_names:
        if a != daxes[0] and mesh.shape[a] > 1:
            return None
    return daxes[0]


def effective(cfg: CommsConfig, mesh) -> CommsConfig:
    """Downgrade int8 -> fp32 (with a warning) on meshes the exchange does
    not support: model-parallel axes > 1 (params not replicated over the
    exchange axis) or a single data shard (nothing to exchange). Keeps
    `TFDE_GRAD_TRANSPORT=int8 tools/tier1.sh` green across every strategy
    instead of exploding mid-suite."""
    if cfg.transport != "int8":
        return cfg
    axis = data_axis(mesh)
    if axis is None:
        log.warning(
            "grad_transport='int8' needs a pure-DP mesh (one data axis, "
            "replicated params); mesh %s is not — falling back to fp32",
            dict(mesh.shape),
        )
        return dataclasses.replace(cfg, transport="fp32")
    if mesh.shape[axis] < 2:
        log.warning(
            "grad_transport='int8' with a single data shard has nothing "
            "to exchange — falling back to fp32"
        )
        return dataclasses.replace(cfg, transport="fp32")
    return cfg


# -- leaf partitioning + residual ---------------------------------------------
def _size(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    n = 1
    for d in shape:
        n *= int(d)
    return n


def compress_mask(tree: Any, cfg: CommsConfig) -> Any:
    """Per-leaf bool tree: True = quantized exchange, False = fp32 psum.
    Static (shape-only), so the split compiles into the step."""
    return jax.tree_util.tree_map(
        lambda leaf: _size(leaf) >= cfg.min_elems and _size(leaf) > 0, tree
    )


def init_residual(params: Any, cfg: CommsConfig) -> Any:
    """Fresh error-feedback residual: zeros_like for compressed leaves, a
    4-byte scalar placeholder for fp32 leaves (keeps the pytree structure
    congruent with params so tree_maps stay trivial)."""
    mask = compress_mask(params, cfg)
    return jax.tree_util.tree_map(
        lambda leaf, m: (
            jnp.zeros(leaf.shape, jnp.float32) if m
            else jnp.zeros((), jnp.float32)
        ),
        params, mask,
    )


# -- flat packing -------------------------------------------------------------
def pack(leaves: Sequence[jax.Array]) -> Tuple[jax.Array, List[Tuple]]:
    """Flatten + concat a leaf list into one fp32 vector; returns
    (vec, shapes) with shapes feeding `unpack`. One buffer per collective
    is the whole point: the collective count stays fixed no matter how
    many tensors the model has."""
    shapes = [tuple(l.shape) for l in leaves]
    if not leaves:
        return jnp.zeros((0,), jnp.float32), shapes
    flat = [l.astype(jnp.float32).reshape(-1) for l in leaves]
    return jnp.concatenate(flat) if len(flat) > 1 else flat[0], shapes


def unpack(vec: jax.Array, shapes: Sequence[Tuple]) -> List[jax.Array]:
    out, off = [], 0
    for shape in shapes:
        n = 1
        for d in shape:
            n *= int(d)
        out.append(jax.lax.dynamic_slice_in_dim(vec, off, n).reshape(shape))
        off += n
    return out


def psum_packed(leaves: Sequence[jax.Array], axis: str) -> List[jax.Array]:
    """Sum a list of small arrays across the data axis in ONE fp32 psum
    (inside shard_map). The fp32 sidecar of the int8 exchange: small grad
    leaves, loss/metric/weight scalars, BatchNorm stats."""
    vec, shapes = pack(leaves)
    if vec.size == 0:
        return list(leaves)
    return unpack(jax.lax.psum(vec, axis), shapes)


# -- the quantized exchange ---------------------------------------------------
def _round(x: jax.Array, rng: Optional[jax.Array]) -> jax.Array:
    if rng is None:
        return jnp.round(x)
    return quant_lib.stochastic_round(x, rng)


def _int8_scatter_phase(
    vec: jax.Array,
    residual: jax.Array,
    cfg: CommsConfig,
    axis: str,
    nshards: int,
    rng: Optional[jax.Array] = None,
):
    """Stages 1-2 of the exchange (shared scales + int8 reduce-scatter),
    shared between `int8_reduce` (which re-quantizes and all-gathers the
    gradient back) and `int8_scatter` (ZeRO weight-update sharding,
    parallel/zero.py: the owner chunk feeds the optimizer directly and the
    all-gather carries updated params instead). Returns
    (t, q, scale, partial, overflow, padded, chunk, idx)."""
    if nshards < 2:
        raise ValueError("int8_reduce needs >= 2 shards")
    length = vec.shape[0]
    t = vec.astype(jnp.float32) + residual.astype(jnp.float32)
    quantum = nshards * cfg.block
    padded = -(-max(length, 1) // quantum) * quantum
    if padded != length:
        t = jnp.pad(t, (0, padded - length))
    blocks = t.reshape(-1, cfg.block)                       # [P/B, B]

    # 1. shared per-block scale: pmax of the local absmaxes. Shared scales
    # make the int8 payload summable on the wire — psum_scatter of q is
    # EXACTLY the dequantized sum, no per-hop dequant/requant needed.
    amax = jnp.max(jnp.abs(blocks), axis=1)
    # a non-finite input must trip the overflow flag ON EVERY DEVICE, and
    # NaN through a max-reduce is implementation-defined — so poison the
    # local absmaxes with +inf (which max propagates deterministically);
    # the flag is then derived only from post-collective values that are
    # bit-identical across the ring (gmax here, full_s below).
    amax = jnp.where(jnp.all(jnp.isfinite(t)), amax, jnp.inf)
    gmax = jax.lax.pmax(amax, axis)                         # [P/B]
    overflow = jnp.any(~jnp.isfinite(gmax)).astype(jnp.float32)
    scale = jnp.maximum(gmax, 1e-12) / 127.0
    r1 = None if (rng is None or not cfg.stochastic) else jax.random.fold_in(rng, 1)
    q = jnp.clip(_round(blocks / scale[:, None], r1), -127, 127)
    q = q.astype(jnp.int8)

    # 2. reduce-scatter the int8 payload; int32 accumulation is exact
    sums = jax.lax.psum_scatter(
        q.reshape(padded).astype(jnp.int32), axis,
        scatter_dimension=0, tiled=True,
    )                                                       # [C] int32
    chunk = padded // nshards
    cblocks = chunk // cfg.block
    idx = jax.lax.axis_index(axis)
    my_scale = jax.lax.dynamic_slice_in_dim(scale, idx * cblocks, cblocks)
    partial = sums.astype(jnp.float32).reshape(-1, cfg.block) * my_scale[:, None]
    return t, q, scale, partial, overflow, padded, chunk, idx


def int8_scatter(
    vec: jax.Array,
    residual: jax.Array,
    cfg: CommsConfig,
    axis: str,
    nshards: int,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The scatter-only half of the exchange, for the sharded weight
    update (parallel/zero.py): returns (owner_chunk [padded/N] — the EXACT
    dequantized partial sum of the chunk this device owns, new_residual
    [L] — the input-side quantization error only (there is no
    re-quantization leg; the chunk feeds the optimizer at full fp32),
    overflow flag). `vec` must already be padded to the layout quantum
    contract or shorter — padding appends zeros, which quantize to zero.

    Collectives: pmax + psum_scatter (2; the trailing all-gather of the
    gradient is replaced by the caller's all-gather of updated params).
    The EF identity still holds: sum_dev(new_residual) + concat_of_chunks
    == sum_dev(vec + residual).
    """
    length = vec.shape[0]
    t, q, scale, partial, overflow, padded, chunk, idx = _int8_scatter_phase(
        vec, residual, cfg, axis, nshards, rng
    )
    deq_in = (q.astype(jnp.float32) * scale[:, None]).reshape(padded)
    new_res = (t - deq_in)[:length]
    return partial.reshape(chunk), new_res, overflow


def int8_reduce(
    vec: jax.Array,
    residual: jax.Array,
    cfg: CommsConfig,
    axis: str,
    nshards: int,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The EQuARX-style exchange, called INSIDE a shard_map body.

    `vec` is this device's local contribution (already in final units:
    sum over devices == the desired global gradient) and `residual` the
    error-feedback carry from the previous step, both [L] fp32. Returns
    (global_sum [L] — bit-identical on every device, new_residual [L] —
    per-device, overflow flag — 1.0 when a quantizer scale went
    non-finite, i.e. the incoming gradients held NaN/Inf; the numerics
    sentry trips on it rather than letting saturation pass silently).

    Collectives: pmax (shared block scales) + psum_scatter (int8 payload,
    int32 accumulator) + all_gather x2 (re-quantized chunks + scales).
    """
    length = vec.shape[0]
    t, q, scale, partial, overflow, padded, chunk, idx = _int8_scatter_phase(
        vec, residual, cfg, axis, nshards, rng
    )

    # 3. re-quantize the owned chunk's partial sums (fresh blockwise scale
    # — the sum's dynamic range grew by up to nshards)
    am2 = jnp.max(jnp.abs(partial), axis=1)
    am2 = jnp.where(jnp.all(jnp.isfinite(partial)), am2, jnp.inf)
    s2 = jnp.maximum(am2, 1e-12) / 127.0
    r2 = None if (rng is None or not cfg.stochastic) else jax.random.fold_in(rng, 2)
    q2 = jnp.clip(_round(partial / s2[:, None], r2), -127, 127)
    q2 = q2.astype(jnp.int8)

    # 4. all-gather the int8 chunks + scales; every device reconstructs
    # the same bytes -> the same averaged gradient (replicas cannot drift)
    full_q = jax.lax.all_gather(q2.reshape(chunk), axis, tiled=True)
    full_s = jax.lax.all_gather(s2, axis, tiled=True)
    overflow = jnp.maximum(
        overflow, jnp.any(~jnp.isfinite(full_s)).astype(jnp.float32)
    )
    out = (full_q.astype(jnp.float32).reshape(-1, cfg.block)
           * full_s[:, None]).reshape(padded)

    # error feedback: what MY quantizer dropped (input side), plus the
    # re-quantization error of the chunk I own — summed over devices the
    # residuals equal the total compression error, so next step's
    # transmission re-injects all of it
    deq_in = (q.astype(jnp.float32) * scale[:, None]).reshape(padded)
    new_res = t - deq_in
    out_err = (partial - q2.astype(jnp.float32) * s2[:, None]).reshape(chunk)
    own = jax.lax.dynamic_slice_in_dim(new_res, idx * chunk, chunk)
    new_res = jax.lax.dynamic_update_slice_in_dim(
        new_res, own + out_err, idx * chunk, 0
    )
    return out[:length], new_res[:length], overflow


# -- analytic wire-byte accounting --------------------------------------------
def comm_bytes(tree: Any, cfg: CommsConfig, nshards: int,
               opt_sharding: str = "replicated") -> dict:
    """Per-step gradient-exchange bytes on the wire, per device, for the
    fp32 ring vs the int8 transport — the numbers behind the
    `comm/bytes_per_step_{fp32,int8}` gauges and the bench `comms` config.

    Ring cost model: an all-reduce moves 2(N-1)/N bytes-per-payload-byte,
    a reduce-scatter or all-gather (N-1)/N. The int8 path pays
    reduce-scatter + all-gather on the 1-byte payload plus the fp32 scale
    sidecars (pmax of block absmaxes, all-gather of re-quant scales).

    Under `opt_sharding='shard'` (parallel/zero.py) the dataflow changes:
    the big segment is only reduce-SCATTERED (fp32 or int8; no gradient
    all-gather, no re-quant leg), and a trailing fp32 all-gather moves the
    updated params (both padded segments + one norm scalar per shard)
    instead — the `param_gather` key, folded into both transports' totals
    so the `comm/bytes_per_step_*` gauges stay truthful."""
    nshards = max(int(nshards), 1)
    ring = 2.0 * (nshards - 1) / nshards
    half = (nshards - 1) / nshards
    mask = compress_mask(tree, cfg)
    leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda l, m: (_size(l), bool(m)), tree, mask),
        is_leaf=lambda x: isinstance(x, tuple),
    )
    big = sum(n for n, m in leaves if m)
    small = sum(n for n, m in leaves if not m)
    quantum = nshards * cfg.block
    big_pad = -(-big // quantum) * quantum if big else 0
    blocks = big_pad // cfg.block
    if opt_sharding == "shard":
        small_pad = -(-small // nshards) * nshards if small else 0
        # fp32 all-gather of updated params: both segments + N norm scalars
        gather = 4.0 * half * (big_pad + small_pad + nshards)
        fp32_bytes = (
            4.0 * ring * small        # packed fp32 sidecar psum
            + 4.0 * half * big_pad    # fp32 reduce-scatter of the big seg
            + gather
        )
        int8_bytes = (
            4.0 * ring * small        # packed fp32 sidecar psum
            + 1.0 * half * big_pad    # int8 reduce-scatter
            + 4.0 * ring * blocks     # pmax of block absmaxes
            + gather
        )
    else:
        gather = 0.0
        fp32_bytes = 4.0 * ring * (big + small)
        int8_bytes = (
            4.0 * ring * small            # packed fp32 sidecar psum
            + 1.0 * half * big_pad        # int8 reduce-scatter
            + 1.0 * half * big_pad        # int8 all-gather
            + 4.0 * ring * blocks         # pmax of block absmaxes
            + 4.0 * half * blocks         # all-gather of re-quant scales
        )
    return {
        "fp32": fp32_bytes,
        "int8": int8_bytes if cfg.transport == "int8" else fp32_bytes,
        "ratio": (int8_bytes / fp32_bytes) if fp32_bytes else 1.0,
        "param_gather": gather,
        "compressed_elems": big,
        "fp32_elems": small,
    }
