"""Activation-sharding annotations — the model side of the partitioning story.

Strategies (parallel/strategies.py) declare where *weights* live; models
declare where *activations* live by calling `constrain(x, ...axes)` at layer
boundaries. Both speak mesh-axis names (runtime/mesh.AXIS_ORDER), and the XLA
SPMD partitioner meets in the middle, inserting the collectives the reference
delegated to NCCL/gRPC (SURVEY.md §2b).

The helper is deliberately forgiving: axis names absent from the active mesh
degrade to `None` (replicated), and with no active mesh it is the identity —
so the same model code runs single-chip, DP, FSDP, TP, and SP unchanged. The
active mesh is set by `use_axes(mesh)` (strategies' step factories do this) or
inherited from an enclosing `jax.sharding.use_mesh`.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfde_tpu.utils import compat as _compat

Axis = Union[str, Sequence[str], None]

_state = threading.local()


def current_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_axes(mesh: Optional[Mesh]):
    """Make `mesh` the target of `constrain` calls in this thread."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def _filter_spec(mesh: Mesh, axes: Sequence[Axis]) -> P:
    """Drop axis names the mesh doesn't have; collapse empty tuples to None."""
    out = []
    for a in axes:
        if a is None:
            out.append(None)
            continue
        names = (a,) if isinstance(a, str) else tuple(a)
        names = tuple(n for n in names if n in mesh.axis_names)
        if not names:
            out.append(None)
        elif len(names) == 1:
            out.append(names[0])
        else:
            out.append(names)
    return P(*out)


def constrain(x: jax.Array, *axes: Axis) -> jax.Array:
    """`with_sharding_constraint(x, P(*axes))` against the active mesh.

    Identity when no mesh is active or every named axis is absent — model
    code stays mesh-agnostic. `axes` may be shorter than `x.ndim`; trailing
    dims replicate.
    """
    mesh = current_mesh()
    if mesh is None:
        return x
    spec = _filter_spec(mesh, tuple(axes) + (None,) * (x.ndim - len(axes)))
    if all(s is None for s in spec):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def vary_over(x: jax.Array, axes: Sequence[str]) -> jax.Array:
    """Promote `x` to device-varying over exactly the axes it lacks from
    `axes` (jax vma typing inside shard_map regions): carries entering a
    fori_loop/scan must match the loop body's variance, and psums demand
    their operands vary over the reduced axes. Shared by the pipeline's
    reductions and ring attention's accumulators."""
    have = _compat.vma_of(x)
    missing = tuple(a for a in axes if a not in have)
    return _compat.pcast(x, missing, to="varying") if missing else x


def batch_axes() -> tuple:
    """The axis-name tuple activations' batch dim is split over: ('data',
    'fsdp') — mirrors sharding.batch_spec so activation constraints agree
    with the input sharding."""
    return ("data", "fsdp")


@contextlib.contextmanager
def manual_seq(ring_size: int, vary_axes: Sequence[str] = ()):
    """Mark this thread as INSIDE a fully-manual region whose 'seq' axis is
    manual with `ring_size` shards — the pp x sp composition signal
    (models/pipelined.py sets it around stage bodies; ops/attention.py
    dispatches to ring_attention_manual when it is set, since the usual
    mesh-based dispatch sees no mesh inside a fully-manual shard_map).
    `vary_axes`: every manual axis in play, for accumulator variance."""
    prev = getattr(_state, "manual_seq", None)
    _state.manual_seq = (int(ring_size), tuple(vary_axes))
    try:
        yield
    finally:
        _state.manual_seq = prev


def manual_seq_info() -> Optional[tuple]:
    """(ring_size, vary_axes) when inside a manual_seq region, else None."""
    return getattr(_state, "manual_seq", None)
