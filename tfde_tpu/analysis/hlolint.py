"""Lowered-program linter: invariant census over StableHLO text.

Given any jitted callable + example args, lower it (trace only — no
compile, no execution) and extract the invariants the repo used to pin
ad hoc in scattered tests:

- **Collective census** — all-reduce / reduce-scatter / all-gather /
  collective-permute counts AND payload bytes (result-tensor bytes per
  occurrence). Counting uses the same quoted-token convention the old
  `tests/test_comms.py` string pins used (``'"stablehlo.all_reduce"'``),
  so migrated budgets are bit-compatible, with a fallback to the pretty
  non-generic spelling for ops StableHLO prints unquoted.
- **Donation verification** — declared `donate_argnums` must survive to
  ``tf.aliasing_output`` attributes in the lowered module; a program
  that declares donation but aliases nothing has silently lost its
  in-place update (double memory at runtime).
- **Host-callback ban** — ``callback``/``outfeed``/``infeed`` markers
  mean a host round-trip inside a hot program. Allowed only by explicit
  per-program allowance (the sentry flag poll and the roofline tile
  counter are the two legitimate users in this codebase, and both keep
  their callbacks OUT of the fused step by design — so the default
  allowance is zero).
- **Dtype policy** — no f64 tensor anywhere (a silent x2 on bytes and
  a ~10x on TPU throughput), and a census of bf16→f32 converts so an
  activation-path upcast shows up as a baseline diff (deliberate logit
  upcasts exist, so converts are counted, not banned).
- **Large replicated constants** — a ``stablehlo.constant`` above the
  threshold is a table baked into the program (replicated on every
  device and re-shipped on every donation miss); it should be an
  argument instead.

Two consumption modes:

1. Direct: ``census(fn, *args)`` / ``lint(name, fn, args, ...)`` — used
   by tests and by `tools/lintgate.py`'s constructed train-step matrix.
2. The registration seam: `lifecycle.py` (train_step first compile) and
   `server.py._mem_register` (decode scan, cold/warm/primed prefill
   waves) call :func:`offer` with the same (fn, args, donated) they hand
   to memwatch. Offers are recorded only when the seam is armed
   (``TFDE_HLOLINT=1`` or :func:`arm`) — zero cost in normal runs — and
   interrogated lazily by :func:`collect`, so the linter sees exactly
   the hot programs the process actually compiled, at the shapes it
   compiled them.

Arguments are snapshotted as avals (`jax.ShapeDtypeStruct`, sharding
preserved) at offer time: donated buffers are deleted after the real
call, but lowering needs only shapes/dtypes/shardings.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from tfde_tpu import knobs

log = logging.getLogger(__name__)

#: collective ops censused, in (field, stablehlo op) pairs
_COLLECTIVES = (
    ("all_reduce", "stablehlo.all_reduce"),
    ("reduce_scatter", "stablehlo.reduce_scatter"),
    ("all_gather", "stablehlo.all_gather"),
    ("collective_permute", "stablehlo.collective_permute"),
)

#: bytes per element for MLIR tensor element types (i1 counts a byte —
#: that is what a packed predicate costs in practice on TPU)
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "f8E4M3FN": 1, "f8E5M2": 1,
}

_TENSOR_RE = re.compile(r"tensor<(?:([0-9]+(?:x[0-9]+)*)x)?([a-zA-Z][a-zA-Z0-9]*)>")
_F64_RE = re.compile(r"tensor<(?:[0-9]+(?:x[0-9]+)*x)?f64>")
_CONVERT_RE = re.compile(
    r"stablehlo\.convert[^\n]*:\s*\(tensor<[^>]*bf16>\)\s*->\s*tensor<[^>]*f32>")
_CONST_RE = re.compile(
    r"stablehlo\.constant[^\n]*?:\s*(tensor<[^>]+>)")

#: default large-constant threshold: 1 MiB baked into the program text
LARGE_CONSTANT_BYTES = 1 << 20


def _tensor_bytes(type_str: str) -> int:
    """``tensor<4x784xf32>`` -> 12544. Unknown element types count 0."""
    m = _TENSOR_RE.search(type_str)
    if not m:
        return 0
    dims, elem = m.groups()
    n = 1
    if dims:
        for d in dims.split("x"):
            n *= int(d)
    return n * _DTYPE_BYTES.get(elem, 0)


def _result_types(text: str, start: int) -> str:
    """The result-type tail of the op whose name starts at `start`:
    scan forward to the first top-level ``-> `` and return the rest of
    that line. Handles both the generic region form (``}) : (...) ->
    ...``) and single-line ops (``... : (...) -> tensor<...>``)."""
    arrow = text.find("-> ", start)
    if arrow < 0:
        return ""
    eol = text.find("\n", arrow)
    return text[arrow + 3:eol if eol > 0 else len(text)]


@dataclasses.dataclass
class Census:
    """One lowered program's invariant census. Counts use the exact
    quoted-token convention of the legacy test pins."""

    all_reduce: int = 0
    reduce_scatter: int = 0
    all_gather: int = 0
    collective_permute: int = 0
    #: payload = result-tensor bytes summed over occurrences, per kind
    collective_bytes: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: host-boundary markers: 'callback' + 'outfeed' + 'infeed' tokens
    callbacks: int = 0
    #: count of tf.aliasing_output attrs (donations that survived)
    aliased_outputs: int = 0
    f64_tensors: int = 0
    bf16_to_f32_converts: int = 0
    #: [(bytes, "tensor<...>")] constants above LARGE_CONSTANT_BYTES
    large_constants: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list)

    @property
    def collective_counts(self) -> Tuple[int, int, int]:
        """(all_reduce, reduce_scatter, all_gather) — the budget triple
        the comms/ZeRO tests pin."""
        return (self.all_reduce, self.reduce_scatter, self.all_gather)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["large_constants"] = [[b, t] for b, t in self.large_constants]
        return d


def census_text(text: str) -> Census:
    """Walk one lowered module's text and extract the census."""
    c = Census()
    for field, op in _COLLECTIVES:
        quoted = f'"{op}"'
        count = text.count(quoted)
        token = quoted
        if count == 0:
            # pretty (non-generic) print: `stablehlo.all_gather %x ...`
            token = op + " "
            count = text.count(token)
        setattr(c, field, count)
        payload = 0
        pos = 0
        for _ in range(count):
            pos = text.find(token, pos)
            if pos < 0:
                break
            payload += _tensor_bytes(_result_types(text, pos))
            pos += len(token)
        if count:
            c.collective_bytes[field] = payload
    c.callbacks = (text.count("callback") + text.count("outfeed")
                   + text.count("infeed"))
    c.aliased_outputs = text.count("tf.aliasing_output")
    c.f64_tensors = len(_F64_RE.findall(text))
    c.bf16_to_f32_converts = len(_CONVERT_RE.findall(text))
    for m in _CONST_RE.finditer(text):
        nbytes = _tensor_bytes(m.group(1))
        if nbytes >= LARGE_CONSTANT_BYTES:
            c.large_constants.append((nbytes, m.group(1)))
    return c


def lower_text(fn, args=(), kwargs=None) -> str:
    """Lower a jitted callable (or a functools.partial over one) at the
    given args and return the StableHLO module text. Lowering only — the
    program is never compiled or run — under `recompile.suppress()` so
    lint-time traces never count against the jit-cache-miss sentinel."""
    from tfde_tpu.observability import recompile

    kwargs = kwargs or {}
    if isinstance(fn, functools.partial):
        inner, bound_args, bound_kw = fn.func, fn.args, dict(fn.keywords)
        bound_kw.update(kwargs)
        args, kwargs, fn = (*bound_args, *args), bound_kw, inner
    if not hasattr(fn, "lower"):
        raise TypeError(
            f"{fn!r} is not a jitted callable (no .lower); wrap it in "
            f"jax.jit or pass the jitted attribute")
    with recompile.suppress():
        return fn.lower(*args, **kwargs).as_text()


def census(fn, *args, **kwargs) -> Census:
    """Lower + census in one call — the helper `tests/test_comms.py` /
    `tests/test_zero.py` consume instead of private string matching."""
    return census_text(lower_text(fn, args, kwargs))


# -- lint policy --------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Policy:
    """Per-program lint policy. The defaults are the house invariants;
    per-program exceptions are declared in an allow-table, never by
    loosening the default."""

    #: host-boundary markers tolerated in this program (the allow-list:
    #: sentry flag poll / roofline tile counter programs declare theirs)
    allow_callbacks: int = 0
    #: f64 is never OK on TPU-shaped programs
    allow_f64: bool = False
    #: constants at/above this many bytes are violations
    max_constant_bytes: int = LARGE_CONSTANT_BYTES
    #: when the program declares donation, at least one output alias
    #: must survive lowering
    require_donation_aliases: bool = True


#: program-name -> Policy exceptions. The ONLY legitimate host-callback
#: users keep their callbacks out of the registered hot programs today,
#: so this table is empty — it exists so the next exception is an
#: explicit, reviewable line instead of a loosened default.
ALLOW: Dict[str, Policy] = {}


@dataclasses.dataclass
class Report:
    """One linted program: its census plus any policy violations."""

    name: str
    census: Census
    violations: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> dict:
        return {"name": self.name, "census": self.census.as_dict(),
                "violations": list(self.violations)}


def _count_donated_leaves(donated) -> int:
    import jax

    return sum(1 for leaf in jax.tree_util.tree_leaves(donated)
               if hasattr(leaf, "shape"))


def lint(name: str, fn=None, args=(), kwargs=None, donated=None,
         policy: Optional[Policy] = None, text: Optional[str] = None) -> Report:
    """Lint one program. Pass either the jitted `fn` + `args` or a
    pre-lowered `text`. `donated` is the pytree the caller declared via
    `donate_argnums` (None = program donates nothing)."""
    policy = policy or ALLOW.get(name, Policy())
    if text is None:
        text = lower_text(fn, args, kwargs)
    c = census_text(text)
    violations: List[str] = []
    if c.callbacks > policy.allow_callbacks:
        violations.append(
            f"{name}: {c.callbacks} host-callback marker(s) in lowered "
            f"program (allowance {policy.allow_callbacks}) — a host "
            f"round-trip inside a hot program; if deliberate, add an "
            f"analysis.hlolint.ALLOW entry for {name!r}")
    if not policy.allow_f64 and c.f64_tensors:
        violations.append(
            f"{name}: {c.f64_tensors} f64 tensor(s) in lowered program — "
            f"the dtype policy bans f64 (silent 2x bytes; cast the "
            f"offending input or enable jax_enable_x64 nowhere)")
    donated_leaves = _count_donated_leaves(donated)
    if (policy.require_donation_aliases and donated_leaves
            and c.aliased_outputs == 0):
        violations.append(
            f"{name}: declares {donated_leaves} donated buffer(s) but "
            f"lowered program aliases 0 outputs — donation was dropped "
            f"(shape/dtype mismatch between donated input and output, or "
            f"the donated arg is unused); the program will hold both "
            f"copies live")
    for nbytes, type_str in c.large_constants:
        if nbytes >= policy.max_constant_bytes:
            violations.append(
                f"{name}: {nbytes}-byte constant {type_str} baked into "
                f"the program (threshold {policy.max_constant_bytes}) — "
                f"replicated on every device; pass it as an argument")
    return Report(name=name, census=c, violations=violations)


# -- the registration seam ----------------------------------------------------
@dataclasses.dataclass
class _Offer:
    name: str
    fn: Any
    args: Tuple
    kwargs: Dict
    donated_leaves: int


_lock = threading.Lock()
_offers: Dict[str, _Offer] = {}
_armed: Optional[bool] = None  # None = defer to TFDE_HLOLINT


def armed() -> bool:
    """Whether :func:`offer` records anything. Defaults to the
    ``TFDE_HLOLINT`` flag (off: the seam costs one dict probe)."""
    if _armed is not None:
        return _armed
    return knobs.env_flag("TFDE_HLOLINT")


def arm(on: bool = True) -> None:
    """Explicitly arm/disarm the seam (overrides TFDE_HLOLINT)."""
    global _armed
    _armed = on


def reset() -> None:
    """Drop recorded offers and the explicit arm state (tests)."""
    global _armed
    with _lock:
        _offers.clear()
    _armed = None


def _aval(leaf):
    import jax

    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return leaf  # static / non-array leaf: keep as-is
    try:
        # keep the sharding only when it actually constrains placement
        # (committed / mesh-sharded arrays); an uncommitted leaf's
        # default single-device sharding would conflict with the rest
        sharding = getattr(leaf, "sharding", None)
        committed = getattr(leaf, "_committed", False)
        if sharding is not None and (
                committed or isinstance(sharding, jax.sharding.NamedSharding)):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        return jax.ShapeDtypeStruct(shape, dtype)
    except Exception:  # noqa: BLE001 — exotic leaf: lower with it live
        return leaf


def offer(name: str, fn, args=(), kwargs=None, donated=None) -> None:
    """Record one hot program for later interrogation. Called from the
    same seams that feed memwatch (`lifecycle.py` train_step first
    compile, `server.py._mem_register`), with the same (fn, args,
    donated). No-op unless :func:`armed`; args are snapshotted as avals
    so the offer stays valid after the donated buffers die. Never
    raises — the seam must not take the caller down."""
    if not armed():
        return
    with _lock:
        if name in _offers:
            return
    try:
        import jax

        a = tuple(jax.tree_util.tree_map(_aval, tuple(args)))
        k = {key: jax.tree_util.tree_map(_aval, val)
             for key, val in (kwargs or {}).items()}
        o = _Offer(name=name, fn=fn, args=a, kwargs=k,
                   donated_leaves=_count_donated_leaves(donated))
    except Exception as e:  # noqa: BLE001
        log.warning("hlolint: could not snapshot offer %s: %s", name, e)
        return
    with _lock:
        _offers.setdefault(name, o)


def offers() -> Tuple[str, ...]:
    with _lock:
        return tuple(sorted(_offers))


def collect() -> Dict[str, Report]:
    """Lint every recorded offer; returns {name: Report}. A program that
    fails to lower reports that as its violation rather than raising —
    the gate should show every program's status, not stop at the first."""
    with _lock:
        pending = list(_offers.values())
    out: Dict[str, Report] = {}
    for o in pending:
        try:
            rep = lint(o.name, o.fn, o.args, o.kwargs,
                       policy=ALLOW.get(o.name))
            # donated pytrees are snapshotted as a leaf count at offer
            # time (the buffers are long dead); apply the dropped-
            # donation check from that count
            if (o.donated_leaves and rep.census.aliased_outputs == 0
                    and ALLOW.get(o.name, Policy()).require_donation_aliases):
                rep.violations.append(
                    f"{o.name}: declares {o.donated_leaves} donated "
                    f"buffer(s) but lowered program aliases 0 outputs — "
                    f"donation was dropped")
        except Exception as e:  # noqa: BLE001
            rep = Report(name=o.name, census=Census(),
                         violations=[f"{o.name}: could not lower for "
                                     f"lint: {e}"])
        out[o.name] = rep
    return out
