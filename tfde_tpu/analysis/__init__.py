"""Static analysis over the framework's lowered programs and source tree.

Two passes, both gated in tier-1 by `tools/lintgate.py`:

- `analysis.hlolint` — the lowered-program linter: census every
  collective (count + payload bytes), verify declared donations survive
  to output aliases, ban host callbacks outside an allow-list, enforce
  the dtype policy (no f64, surface bf16->f32 upcasts), and flag large
  replicated constants. The same helper backs the HLO pins in
  `tests/test_comms.py`/`tests/test_zero.py`.
- `tools/tfdelint.py` — the AST project lint (lock discipline for
  threaded classes, greedy-path `jax.random.split` ban, TFDE_* knob
  audit against `tfde_tpu/knobs.py`). Lives in tools/ because it reads
  the source tree, not programs.
"""

from tfde_tpu.analysis import hlolint  # noqa: F401
