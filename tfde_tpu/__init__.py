"""tfde_tpu — a TPU-native distributed-training framework.

A from-scratch JAX/XLA/pjit framework providing the capabilities of the
reference `lowc1012/tensorflow-distributed-example` (three TF distributed
training recipes on MNIST: multi-worker collective all-reduce, parameter-server
training, and mirrored single-host data parallelism), re-designed TPU-first:

- SPMD over a `jax.sharding.Mesh` (ICI within a slice, DCN across slices)
  instead of NCCL/gRPC collectives.
- `jit`/`pjit`-compiled train steps; gradient aggregation via XLA collectives
  (`lax.psum`) inserted by the partitioner, not hand-written rings.
- Flax modules for the model zoo (reference CNNs plus ResNet-50, ViT-B/16 and
  BERT-base scale configs).
- Per-host sharded input pipelines with on-device double-buffered prefetch
  (the tf.data analog).
- Estimator-style lifecycle: `train_and_evaluate` with eval throttling,
  periodic checkpointing (Orbax, auto-resume), TensorBoard summaries, and a
  serving export artifact (landing per SURVEY.md §7's layer order).

See SURVEY.md at the repo root for the blueprint and reference file:line
citations throughout the docstrings.
"""

__version__ = "0.1.0"

import jax as _jax

# Sharding-invariant RNG is a framework invariant: params initialized under
# an FSDP/TP sharding must equal the unsharded init, or "numerics identical
# across strategies" dies at step 0. Newer jax defaults (or hardwires) this
# on; older releases default it off — pin it. No-op where the flag is gone.
try:
    _jax.config.update("jax_threefry_partitionable", True)
except AttributeError:
    pass

# Surface TFDE_* typos (unregistered names in the environment) at import,
# before any knob read silently runs a default the operator didn't ask for.
from tfde_tpu import knobs as _knobs

_knobs.warn_unknown_env()

from tfde_tpu.runtime.mesh import MeshSpec, make_mesh  # noqa: F401
from tfde_tpu.runtime.cluster import ClusterInfo, bootstrap  # noqa: F401
